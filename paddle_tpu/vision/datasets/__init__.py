"""paddle.vision.datasets analog (reference: python/paddle/vision/datasets —
mnist.py, cifar.py, flowers.py, voc2012.py; all download-then-parse).

Real parsers for the reference file formats (IDX for MNIST family, pickled
batches for CIFAR) reading local files; no egress here, so missing files
raise with instructions instead of downloading."""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "VOC2012", "DatasetFolder", "ImageFolder"]


def _require(path, name, url):
    if path is None or not os.path.exists(path):
        raise RuntimeError(
            f"{name}: dataset file not found at {path!r}; this environment "
            f"cannot download ({url}). Pass the reference-format file path.")


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"bad IDX image magic {magic} in {path}")
        return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"bad IDX label magic {magic} in {path}")
        return np.frombuffer(f.read(), np.uint8)


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py MNIST."""

    NAME = "MNIST"
    URL = "yann.lecun.com/exdb/mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="numpy"):
        _require(image_path, self.NAME, self.URL)
        _require(label_path, self.NAME, self.URL)
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)
        if len(self.images) != len(self.labels):
            raise ValueError("image/label count mismatch")
        self.transform = transform
        self.backend = backend
        self.mode = mode

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])


class FashionMNIST(MNIST):
    """reference: vision/datasets/mnist.py FashionMNIST (same IDX format)."""

    NAME = "FashionMNIST"
    URL = "fashion-mnist"


class Cifar10(Dataset):
    """reference: vision/datasets/cifar.py Cifar10 — tar.gz of pickled
    batches, each {b'data': [N,3072] uint8, b'labels': [N]}."""

    _KEY = b"labels"
    _TRAIN_RE = "data_batch"
    _TEST_RE = "test_batch"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="numpy"):
        _require(data_file, type(self).__name__, "cifar archive")
        want = self._TRAIN_RE if mode == "train" else self._TEST_RE
        xs, ys = [], []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if want in m.name:
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    xs.append(np.asarray(d[b"data"], np.uint8))
                    ys.append(np.asarray(d[self._KEY], np.int64))
        if not xs:
            raise ValueError(f"no '{want}' members found in {data_file}")
        self.data = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.concatenate(ys)
        self.transform = transform

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        img = self.data[idx].astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])


class Cifar100(Cifar10):
    """reference: cifar.py Cifar100 (fine_labels key, train/test pickles)."""

    _KEY = b"fine_labels"
    _TRAIN_RE = "train"
    _TEST_RE = "test"


class _Gated(Dataset):
    _URL = ""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="numpy", **kw):
        _require(data_file, type(self).__name__, self._URL)
        raise NotImplementedError(
            f"{type(self).__name__} parser lands with format fixtures; "
            f"see reference vision/datasets.")


class Flowers(_Gated):
    _URL = "102flowers.tgz"


class VOC2012(_Gated):
    _URL = "VOCtrainval_11-May-2012.tar"


class DatasetFolder(Dataset):
    """<root>/<class>/*.png-style folder dataset (reference:
    vision/datasets/folder.py DatasetFolder). Image decode via numpy-readable
    formats (.npy) or a user loader."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        if not os.path.isdir(root):
            raise RuntimeError(f"DatasetFolder: root {root!r} not found")
        self.classes = sorted(d for d in os.listdir(root)
                              if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        exts = extensions or (".npy",)
        self.samples = []
        for c in self.classes:
            for f in sorted(os.listdir(os.path.join(root, c))):
                path = os.path.join(root, c, f)
                ok = is_valid_file(path) if is_valid_file else \
                    f.lower().endswith(tuple(exts))
                if ok:
                    self.samples.append((path, self.class_to_idx[c]))
        self.loader = loader or (lambda p: np.load(p))
        self.transform = transform

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target


class ImageFolder(DatasetFolder):
    """Unlabeled variant (reference: folder.py ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        if not os.path.isdir(root):
            raise RuntimeError(f"ImageFolder: root {root!r} not found")
        exts = extensions or (".npy",)
        self.samples = []
        for dirpath, _, files in os.walk(root):
            for f in sorted(files):
                path = os.path.join(dirpath, f)
                ok = is_valid_file(path) if is_valid_file else \
                    f.lower().endswith(tuple(exts))
                if ok:
                    self.samples.append((path, -1))
        self.loader = loader or (lambda p: np.load(p))
        self.transform = transform

    def __getitem__(self, idx):
        path, _ = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return [img]
