"""Hybrid-parallel layer library (TP/SP/PP/EP) — SURVEY §2.4 parallelism
strategies, redesigned as GSPMD shardings + shard_map collectives."""
