"""Hybrid-parallel layer library (SURVEY §2.4), TPU-native:
TP/SP = GSPMD shardings; EP = dense GShard dispatch + mesh alltoall;
PP = ppermute schedule (SPMD) or stage-pinned container; CP = ring attention."""
from .mp_layers import (VocabParallelEmbedding, ColumnParallelLinear,  # noqa: F401
                        RowParallelLinear, ParallelCrossEntropy,
                        RNGStatesTracker, get_rng_state_tracker,
                        model_parallel_random_seed)
from .sequence_parallel import (ColumnSequenceParallelLinear,  # noqa: F401
                                RowSequenceParallelLinear, AllGatherOp,
                                ReduceScatterOp,
                                mark_as_sequence_parallel_parameter,
                                register_sequence_parallel_allreduce_hooks)
from .moe import MoELayer, ExpertMLP, top2_gating  # noqa: F401
from .ring_attention import ring_flash_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .pipeline import pipeline_forward, pipeline_call  # noqa: F401
from .pipeline_layer import (PipelineLayer, LayerDesc, SharedLayerDesc,  # noqa: F401
                             PipelineParallel, PipelineParallelWithInterleave,
                             ZeroBubblePipelineParallel)
from .tensor_parallel import TensorParallel, SegmentParallel  # noqa: F401
from .sharding import (group_sharded_parallel, save_group_sharded_model,  # noqa: F401
                       DygraphShardingOptimizer, GroupShardedStage2,
                       GroupShardedStage3, GroupShardedOptimizerStage2,
                       shard_parameters, shard_accumulators)
