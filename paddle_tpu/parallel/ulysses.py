"""Ulysses (DeepSpeed-style) all-to-all sequence parallelism (SURVEY §5
long-context mechanism 2: the reference wires the 'sep' mesh axis through
topology and leaves the attention-level CP algorithms — ring attention AND
Ulysses all-to-all — to PaddleNLP; both are in-core here).

TPU-native: ONE shard_map over 'sep' whose body does
  all_to_all(seq-shard -> head-shard) -> full-sequence flash attention on
  the local head group -> all_to_all back.
The two all-to-alls ride ICI; between them every device sees the FULL
sequence for H/sep heads, so the attention itself needs no communication —
the right trade when S >> H and the ring's per-step latency would dominate.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ._compat import shard_map
from ..core.dispatch import apply_op
from ..distributed.collective import mesh_all_to_all
from ..distributed.fleet.topology import get_hybrid_communicate_group

__all__ = ["ulysses_attention"]


def _ulysses_local(q, k, v, axis_name, causal, scale):
    """Per-shard body. q/k/v local: [B, S/n, H, D] -> out [B, S/n, H, D]."""
    n = jax.lax.psum(1, axis_name)

    def seq_to_heads(x):
        # [B, s, H, D] -> [B, s*n, H/n, D]: tiled all_to_all splits the head
        # axis into n chunks (chunk i -> rank i) and concatenates received
        # seq chunks in rank order — global sequence order, rank-major heads
        return mesh_all_to_all(x, axis_name, split_axis=2, concat_axis=1)

    def heads_to_seq(x):
        # [B, S, H/n, D] -> [B, S/n, H, D]: exact inverse
        return mesh_all_to_all(x, axis_name, split_axis=1, concat_axis=2)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # full-sequence attention on the local head group, BLOCKWISE over K with
    # an online softmax — memory O(S * block), never the dense [S, S]
    # logits this mode exists to avoid at long context
    out = _blockwise_sdpa(qg, kg, vg, causal=causal, scale=scale)
    return heads_to_seq(out.astype(q.dtype))


def _blockwise_sdpa(q, k, v, causal, scale, block=1024):
    """[B, S, H, D] flash-style attention via lax.scan over K blocks."""
    B, S, H, D = q.shape
    blk = min(block, S)
    while S % blk:          # static divisor of S
        blk //= 2
    nk = S // blk
    # bf16 MXU operands + f32 accumulation (native MXU mode; see
    # ring_attention) — scale and softmax statistics stay f32
    kb = k.reshape(B, nk, blk, H, D).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(B, nk, blk, H, D).transpose(1, 0, 2, 3, 4)
    q_pos = jnp.arange(S)

    def body(carry, xs):
        m_prev, l_prev, acc = carry
        kc, vc, j = xs
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kc,
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.DEFAULT) * scale
        if causal:
            k_pos = j * blk + jnp.arange(blk)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, -1e30)
        m_cur = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new[..., None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(vc.dtype), vc,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        return (m_new, l_new, acc), None

    init = (jnp.full((B, H, S), -jnp.inf, jnp.float32),
            jnp.zeros((B, H, S), jnp.float32),
            jnp.zeros((B, H, S, D), jnp.float32))
    (m, l, acc), _ = jax.lax.scan(body, init,
                                  (kb, vb, jnp.arange(nk)))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3)    # [B, H, S, D] -> [B, S, H, D]


def ulysses_attention(q, k, v, causal=True, axis_name="sep", mesh=None):
    """[B, S, H, D] with S sharded over `axis_name`; H must be divisible by
    the axis size. Returns the same sharding."""
    hcg = get_hybrid_communicate_group()
    jmesh = mesh if mesh is not None else hcg.get_mesh().jax_mesh()
    if axis_name not in jmesh.axis_names or \
            jmesh.devices.shape[jmesh.axis_names.index(axis_name)] == 1:
        from ..nn.functional.attention import _sdpa_ref
        return apply_op("ulysses_attention",
                        lambda a, b, c: _sdpa_ref(a, b, c, causal=causal),
                        q, k, v)
    n = jmesh.devices.shape[jmesh.axis_names.index(axis_name)]
    if q.shape[2] % n != 0:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by the "
            f"'{axis_name}' axis size ({n})")
    scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, axis_name, None, None)

    def f(qa, ka, va):
        body = functools.partial(_ulysses_local, axis_name=axis_name,
                                 causal=causal, scale=scale)
        sm = shard_map(body, mesh=jmesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
        return sm(qa, ka, va)

    return apply_op("ulysses_attention", f, q, k, v)
