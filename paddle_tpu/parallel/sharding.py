"""ZeRO / sharding stages (reference: fleet/meta_optimizers/dygraph_optimizer/
dygraph_sharding_optimizer.py:54 (stage 1), fleet/meta_parallel/sharding/
group_sharded_stage2.py:47, group_sharded_stage3.py:85; facade
python/paddle/distributed/sharding/group_sharded.py).

TPU-native: each ZeRO stage is a *placement policy* over the 'sharding' mesh
axis — stage 1 shards optimizer accumulators, stage 2 also gradients (same
placement: grads inherit from params under GSPMD), stage 3 shards the
parameters themselves. XLA's partitioner then emits exactly the
reduce-scatter / all-gather pattern the reference hand-codes.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..distributed.fleet.topology import get_hybrid_communicate_group


def _shard_spec_for(shape, axis="sharding", mesh=None):
    """Shard dim 0 if divisible by the axis size, else replicate."""
    n = mesh.devices.shape[mesh.axis_names.index(axis)]
    if shape and shape[0] % n == 0 and shape[0] >= n:
        return P(*([axis] + [None] * (len(shape) - 1)))
    return P()


def shard_accumulators(optimizer, axis="sharding", mesh=None):
    """ZeRO-1: place every optimizer accumulator sharded on the axis."""
    jmesh = mesh or get_hybrid_communicate_group().get_mesh().jax_mesh()
    if axis not in jmesh.axis_names or \
            jmesh.devices.shape[jmesh.axis_names.index(axis)] == 1:
        return optimizer
    orig_acc = optimizer._acc

    def sharded_acc(name, p, init=None, dtype=None):
        t = orig_acc(name, p, init, dtype)
        arr = t._buf
        if not isinstance(arr, jax.core.Tracer) and \
                getattr(getattr(arr, "sharding", None), "num_devices", 1) == 1:
            spec = _shard_spec_for(tuple(arr.shape), axis, jmesh)
            t._buf = jax.device_put(arr, NamedSharding(jmesh, spec))
        return t

    optimizer._acc = sharded_acc
    return optimizer


def shard_parameters(model, axis="sharding", mesh=None):
    """ZeRO-3: shard parameter storage on the axis (FSDP)."""
    jmesh = mesh or get_hybrid_communicate_group().get_mesh().jax_mesh()
    if axis not in jmesh.axis_names or \
            jmesh.devices.shape[jmesh.axis_names.index(axis)] == 1:
        return model
    for p in model.parameters():
        spec = _shard_spec_for(tuple(p._buf.shape), axis, jmesh)
        p._buf = jax.device_put(p._buf, NamedSharding(jmesh, spec))
    return model


class DygraphShardingOptimizer:
    """ZeRO stage-1 wrapper (reference dygraph_sharding_optimizer.py:54)."""

    def __init__(self, optimizer, hcg=None):
        self._inner_opt = shard_accumulators(optimizer)
        self._hcg = hcg

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero)


class GroupShardedOptimizerStage2(DygraphShardingOptimizer):
    """reference group_sharded_optimizer_stage2.py:53 — optimizer whose
    states live sharded on the sharding axis (same placement policy as
    stage 1; gradients inherit it inside the compiled step).

    Accepts the reference call shape (params, optim, group) as well as the
    stage-1 wrapper's (optimizer, hcg)."""

    def __init__(self, params=None, optim=None, group=None, **kw):
        opt = optim if optim is not None and hasattr(optim, "_acc") else \
            (params if hasattr(params, "_acc") else optim)
        if opt is None or not hasattr(opt, "_acc"):
            raise TypeError("GroupShardedOptimizerStage2 needs an optimizer "
                            "(reference signature: params, optim, group)")
        super().__init__(opt)


class _ShardedModelWrapper:
    """Model wrapper matching the reference GroupShardedStage2/3 call shape:
    wraps the layer, delegates forward/state_dict, and applies the stage's
    placement policy. The reduce-scatter/all-gather traffic the reference
    hand-codes is emitted by XLA from these placements inside the compiled
    train step."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False,
                 **kw):
        self._layers = layer
        self._optimizer = optimizer

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self

    def __getattr__(self, item):
        return getattr(self.__dict__["_layers"], item)


class GroupShardedStage2(_ShardedModelWrapper):
    """reference group_sharded_stage2.py:47 — grad + optimizer-state
    sharding: wraps the model and shards the optimizer's accumulators; grads
    reduce-scatter automatically under GSPMD."""

    def __init__(self, layer, sharding_optimizer=None, group=None,
                 sync_buffers=False, buffer_max_size=2 ** 23, **kw):
        super().__init__(layer, sharding_optimizer, group)
        if sharding_optimizer is not None and not isinstance(
                sharding_optimizer, DygraphShardingOptimizer):
            shard_accumulators(sharding_optimizer)


class GroupShardedStage3(_ShardedModelWrapper):
    """reference group_sharded_stage3.py:85 — parameter sharding (FSDP):
    wraps the model, shards parameter storage AND optimizer state."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False,
                 segment_size=2 ** 20, offload=False, **kw):
        super().__init__(layer, optimizer, group)
        shard_parameters(layer)
        if optimizer is not None:
            shard_accumulators(optimizer)


def group_sharded_parallel(model, optimizer, level="p_g_os", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None, exclude_layer=None):
    """reference: python/paddle/distributed/sharding/group_sharded.py.

    level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3/FSDP).
    """
    if level in ("os", "os_g", "p_g_os"):
        optimizer = shard_accumulators(optimizer)
    if level == "p_g_os":
        model = shard_parameters(model)
    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework.io import save
    save(model.state_dict(), output + ".pdparams")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
