"""Mixture-of-Experts with expert parallelism (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py:263 MoELayer using
global_scatter/global_gather all-to-all; gate kernels phi/kernels/*number_count,
limit_by_capacity, random_routing; spmd rules moe_gate_dispatch/moe_combine).

TPU-native: experts' weights are stacked [E, ...] and sharded on the dedicated
'ep' mesh axis when the hybrid topology has one (falling back to 'mp' on
pre-ep meshes), with the expert FFN hidden dim sharded on 'mp' so TP and EP
compose (reference composes them via moe sub-meshes,
auto_parallel/static/pir_pass.py:368). Token dispatch is a dense
capacity-bucketed einsum (GShard-style) whose all-to-all is emitted by GSPMD
from the shardings. No host-side routing — everything is jit-compatible dense
math on the MXU.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap
from ..nn.layer.layers import Layer
from ..nn.initializer import XavierUniform
from ..nn import functional as F
from .mp_layers import _mp_mesh, _shard_param, _constrain


def _expert_axes():
    """(ep_axis, tp_axis) for expert sharding on the current mesh: experts go
    on 'ep' when the mesh has one (size>1), else 'mp' (pre-ep 5-axis
    topologies); the expert FFN hidden dim additionally shards on 'mp' only
    when ep and mp are both active (TP x EP composition)."""
    mesh = _mp_mesh()
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if sizes.get("ep", 1) > 1:
        return "ep", ("mp" if sizes.get("mp", 1) > 1 else None)
    return "mp", None


def topk_gating(logits, capacity, k=2):
    """GShard-style top-k gating: returns (combine [S,E,C], dispatch mask,
    aux_loss). Generalizes the classic top-2 — slot s assigns each token its
    s-th-choice expert, capacity-limited by cumsum position after the prior
    slots' assignments (reference's number_count/limit_by_capacity/assign_pos
    kernels collapse into this cumsum math; top-k for the DeepSeekMoE/Qwen2
    top-6/top-8 routers).

    logits: [S, E] float32. Dense and jit-friendly.
    """
    S, E = logits.shape
    k = min(k, E)     # argmax over an exhausted row would re-pick expert 0
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    remaining = probs
    prior = jnp.zeros((E,), jnp.float32)     # capacity used by earlier slots
    combine = jnp.zeros((S, E, capacity), jnp.float32)
    gsum = jnp.zeros((S,), jnp.float32)
    aux_loss = None
    for slot in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        remaining = remaining * (1 - mask)
        if slot == 0:
            # aux load-balancing loss (Switch/GShard): top-1 density
            density = jnp.mean(mask, axis=0)
            density_proxy = jnp.mean(probs, axis=0)
            aux_loss = jnp.sum(density * density_proxy) * E
        pos = (jnp.cumsum(mask, axis=0) - 1 + prior) * mask
        mask = mask * (pos < capacity)
        prior = prior + jnp.sum(mask, axis=0)
        g = jnp.sum(probs * mask, axis=-1)
        loc = jnp.sum(pos, axis=-1).astype(jnp.int32)
        sel = jnp.sum(mask, axis=-1)
        cap_oh = jax.nn.one_hot(loc, capacity, dtype=jnp.float32) * sel[:, None]
        combine = combine + (g[:, None, None] * mask[:, :, None]
                             * cap_oh[:, None, :])
        gsum = gsum + g
    combine = combine / jnp.maximum(gsum, 1e-9)[:, None, None]
    dispatch = combine > 0
    return combine, dispatch, aux_loss


def top2_gating(logits, capacity):
    """Classic GShard top-2 (kept as the named entry point)."""
    return topk_gating(logits, capacity, k=2)


class ExpertMLP(Layer):
    """Stacked experts: weights [E, in, hidden] / [E, hidden, in] sharded on mp."""

    def __init__(self, num_experts, d_model, d_hidden, activation=F.gelu):
        super().__init__()
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden],
                                        default_initializer=XavierUniform())
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model],
                                        default_initializer=XavierUniform())
        self.ep_axis, tp = _expert_axes()
        _shard_param(self.w1, P(self.ep_axis, None, tp))
        _shard_param(self.w2, P(self.ep_axis, tp, None))
        self.act = activation

    def forward(self, x):
        """x: [E, C, d_model] expert-major tokens -> [E, C, d_model]."""
        def f(a, w1, w2):
            h = jnp.einsum("ecm,emh->ech", a, w1.astype(a.dtype))
            h = jax.nn.gelu(h)
            return jnp.einsum("ech,ehm->ecm", h, w2.astype(a.dtype))
        return apply_op("expert_mlp", f, x, self.w1, self.w2)


class MoELayer(Layer):
    """reference: moe/moe_layer.py:263. GShard-style gate, top_k selectable
    (top-2 default; DeepSeek/Qwen2 MoE use 6/8)."""

    def __init__(self, d_model, experts=None, num_experts=8, d_hidden=None,
                 gate=None, moe_group=None, mp_group=None, recompute_interval=0,
                 capacity_factor=1.25, top_k=2, name=None):
        super().__init__()
        self.num_experts = num_experts
        self.d_model = d_model
        self.capacity_factor = capacity_factor
        self.top_k = int(top_k)
        self.gate_w = self.create_parameter([d_model, num_experts],
                                            default_initializer=XavierUniform())
        self.experts = experts if experts is not None else \
            ExpertMLP(num_experts, d_model, d_hidden or 4 * d_model)
        self.aux_loss = None

    def forward(self, x):
        b, s, m = x.shape
        S = b * s
        E = self.num_experts
        # GShard capacity scales with the router fan-out: top-k dispatches
        # k*S assignments, so slots must scale by k or most are dropped
        C = int(np.ceil(self.capacity_factor * self.top_k * S / E))
        cap = C

        def f(a, gw):
            flat = a.reshape(S, m)
            logits = flat.astype(jnp.float32) @ gw.astype(jnp.float32)
            combine, dispatch, aux = topk_gating(logits, cap, self.top_k)
            # dispatch tokens -> [E, C, m] (alltoall emitted by GSPMD given the
            # expert-sharded weights downstream)
            exp_in = jnp.einsum("sec,sm->ecm", dispatch.astype(a.dtype), flat)
            return exp_in, combine.astype(jnp.float32), aux

        exp_in, combine, aux = apply_op("moe_dispatch", f, x, self.gate_w)
        # prefer the axis fixed at construction (consistent with the expert
        # weight sharding); if the active mesh no longer has that axis, fall
        # back to what the current mesh supports so _constrain can't KeyError
        ep = getattr(self.experts, "ep_axis", None)
        if ep is None or ep not in _mp_mesh().axis_names:
            ep = _expert_axes()[0]
        exp_in = _constrain(exp_in, P(ep, None, None))
        exp_out = self.experts(exp_in)
        exp_out = _constrain(exp_out, P(ep, None, None))

        def g(eo, comb):
            out = jnp.einsum("sec,ecm->sm", comb.astype(eo.dtype), eo)
            return out.reshape(b, s, m)

        out = apply_op("moe_combine", g, exp_out, combine)
        self.aux_loss = apply_op("moe_aux", lambda l: l, aux)
        return out
