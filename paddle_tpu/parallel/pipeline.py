"""SPMD pipeline schedule over the 'pp' mesh axis (SURVEY §7: "PP = stage-
partitioned program + collective_permute microbatch schedule").

Reference semantics: fleet/meta_parallel/pipeline_parallel.py (1F1B :575,
interleave :1179) built on NCCL p2p. TPU-native replacement: every stage runs
the SAME program under shard_map; stage weights are stacked on a leading [pp]
dim; activations rotate via lax.ppermute. A GPipe fill-drain over M microbatches
completes in M + P - 1 ticks; XLA overlaps the ppermute with compute on ICI.

This powers the homogeneous-transformer fast path; the generic host-driven
PipelineLayer container lives in pipeline_layer.py.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ._compat import shard_map
from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap
from ..distributed.collective import mesh_ppermute


def pipeline_forward(stage_fn, stacked_params, x_micro, *, mesh, axis_name="pp"):
    """Run microbatched GPipe forward.

    stage_fn(params_slice, x) -> y        (same shapes for x and y)
    stacked_params: pytree with leading [P] dim on every leaf (stage-major)
    x_micro: [M, B, ...] microbatches (already embedded — homogeneous stages)
    returns [M, B, ...] outputs from the LAST stage (replicated).
    """
    P_ = mesh.devices.shape[mesh.axis_names.index(axis_name)]

    def body(params, xs):
        # params: local stage slice (leading dim 1); xs: all microbatches
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        idx = jax.lax.axis_index(axis_name)
        M = xs.shape[0]
        n_ticks = M + P_ - 1
        perm = [(i, (i + 1) % P_) for i in range(P_)]

        buf = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)

        def tick(carry, t):
            buf, outs = carry
            # stage 0 ingests microbatch t (when valid)
            m_in = jnp.clip(t, 0, M - 1)
            inject = jnp.where(t < M, 1.0, 0.0).astype(xs.dtype)
            x_in = jnp.where(idx == 0,
                             xs[m_in] * inject + buf * (1 - inject) * 0.0,
                             buf)
            y = stage_fn(params, x_in)
            # last stage's output for microbatch (t - (P-1)) is ready at tick t
            m_out = t - (P_ - 1)
            valid_out = (m_out >= 0) & (m_out < M)
            outs = jax.lax.cond(
                valid_out,
                lambda o: o.at[jnp.clip(m_out, 0, M - 1)].set(y),
                lambda o: o, outs)
            buf_next = mesh_ppermute(y, axis_name, perm)
            return (buf_next, outs), None

        (buf, outs), _ = jax.lax.scan(tick, (buf, outs), jnp.arange(n_ticks))
        # per-stage output shard; only the last stage's slice is meaningful.
        # Returning it SHARDED (leading pp axis) instead of zero+psum avoids
        # an O(M*B*hidden) all-reduce every forward (r2 weak #8): the [P-1]
        # slice below moves just the last stage's copy, and only when a
        # consumer actually needs it elsewhere.
        return outs[None]

    pspec_params = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    f = shard_map(body, mesh=mesh,
                  in_specs=(pspec_params, P()),
                  out_specs=P(axis_name), check_vma=False)
    return f(stacked_params, x_micro)[P_ - 1]


def pipeline_call(stage_fn, stacked_params, x_micro, mesh, axis_name="pp"):
    """Tensor-level wrapper with autograd through the schedule."""
    params_arrays = jax.tree_util.tree_map(
        lambda t: unwrap(t) if isinstance(t, Tensor) else t, stacked_params)
    leaves, treedef = jax.tree_util.tree_flatten(params_arrays)

    def f(x, *param_leaves):
        params = jax.tree_util.tree_unflatten(treedef, param_leaves)
        return pipeline_forward(stage_fn, params, x, mesh=mesh, axis_name=axis_name)

    tensor_leaves = jax.tree_util.tree_flatten(
        stacked_params, is_leaf=lambda x: isinstance(x, Tensor))[0]
    return apply_op("pipeline", f, x_micro, *tensor_leaves)
