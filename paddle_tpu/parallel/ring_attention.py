"""Ring attention for sequence/context parallelism (SURVEY §5 long-context:
the reference provides the 'sep' mesh axis + four-direction p2p
(fleet/base/topology.py:199, pp_utils/four_directions_p2p_communication.py);
ring/blockwise attention itself lives downstream in PaddleNLP. Here it is
in-core and TPU-native: shard_map over the 'sep' axis + lax.ppermute rotating
K/V blocks around the ICI ring, with online-softmax accumulation (flash style,
f32 accumulators)."""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ._compat import shard_map
from ..core.tensor import Tensor
from ..core.dispatch import apply_op
from ..distributed.collective import mesh_ppermute
from ..distributed.fleet.topology import get_hybrid_communicate_group


def _ring_attn_local(q, k, v, axis_name, causal, scale):
    """Per-shard body: q local [B, Sq, H, D]; k/v rotate around the ring.

    Online softmax: keep running (max, sum, acc) in f32 while blocks arrive.
    Causality across blocks is decided by comparing global block offsets.
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Sq, H, D = q.shape

    def attend(carry, kv_and_src):
        m_prev, l_prev, acc = carry
        (kb, vb), src_idx = kv_and_src
        # bf16 MXU operands + f32 accumulation (native MXU mode — upcasting
        # operands to f32 forces the slow multi-pass path); the scale and all
        # softmax statistics stay in f32
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kb,
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.DEFAULT) * scale
        if causal:
            q_pos = my_idx * Sq + jnp.arange(Sq)
            k_pos = src_idx * kb.shape[1] + jnp.arange(kb.shape[1])
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, -jnp.inf)
        m_cur = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        # guard fully-masked rows
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        corr = jnp.exp(jnp.where(jnp.isfinite(m_prev), m_prev - m_safe, -jnp.inf))
        corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
        l_new = l_prev * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(vb.dtype), vb,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        return (m_new, l_new, acc_new)

    m0 = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    acc0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    carry = (m0, l0, acc0)
    kb, vb = k, v
    src = my_idx
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        carry = attend(carry, ((kb, vb), src))
        if step < n - 1:
            kb = mesh_ppermute(kb, axis_name, perm)
            vb = mesh_ppermute(vb, axis_name, perm)
            src = mesh_ppermute(src, axis_name, perm)
    m, l, acc = carry
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_flash_attention(q, k, v, causal=True, axis_name="sep", mesh=None):
    """[B, S, H, D] with S sharded over `axis_name`; returns same sharding."""
    hcg = get_hybrid_communicate_group()
    jmesh = mesh if mesh is not None else hcg.get_mesh().jax_mesh()
    if axis_name not in jmesh.axis_names or \
            jmesh.devices.shape[jmesh.axis_names.index(axis_name)] == 1:
        from ..nn.functional.attention import _sdpa_ref
        return apply_op("ring_attention",
                        lambda a, b, c: _sdpa_ref(a, b, c, causal=causal), q, k, v)
    scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, axis_name, None, None)
    other = tuple(a for a in jmesh.axis_names if a != axis_name)

    def f(qa, ka, va):
        body = functools.partial(_ring_attn_local, axis_name=axis_name,
                                 causal=causal, scale=scale)
        sm = shard_map(body, mesh=jmesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
        return sm(qa, ka, va)

    return apply_op("ring_attention", f, q, k, v)
