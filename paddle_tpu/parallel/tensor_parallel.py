"""TensorParallel model wrapper (reference: fleet/meta_parallel/tensor_parallel.py).

On TPU there is no broadcast-at-init (single controller: one copy of truth);
the wrapper is a passthrough that validates the mp mesh exists.
"""
from __future__ import annotations

from ..nn.layer.layers import Layer


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)


class SegmentParallel(TensorParallel):
    """sep-axis wrapper (reference: fleet/meta_parallel/segment_parallel.py:26)."""
