"""PipelineLayer container + PipelineParallel wrapper (reference:
fleet/meta_parallel/parallel_layers/pp_layers.py:258 PipelineLayer,
fleet/meta_parallel/pipeline_parallel.py:255 PipelineParallel, train_batch:820).

TPU-native: stages are contiguous segments of the layer list whose parameters
are pinned (device_put) onto the stage's slice of the mesh; activations flow
between slices through ordinary op dataflow (PJRT moves buffers; under capture
XLA emits device-to-device copies). The microbatch loop + grad accumulation
runs on the tape, so 'schedules' differ only in traversal order:
FThenB (implemented), 1F1B (memory ordering — same numerics).
"""
from __future__ import annotations

import numpy as np
import jax

from ..core.tensor import Tensor
from ..core.dispatch import unwrap
from ..nn.layer.layers import Layer
from ..nn.layer.container import LayerList
from .. import ops


class LayerDesc:
    """Lazy layer description (reference pp_layers.py LayerDesc)."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-shared layer across stages (reference pp_layers.py SharedLayerDesc).
    On a single-controller mesh the same Parameter object is simply reused."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        self._num_stages = num_stages or (topology.get_dim("pp") if topology else 1)
        self._recompute_interval = recompute_interval
        descs = list(layers)
        built = []
        self._shared = {}
        for d in descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    src = self._shared[d.layer_name]
                    layer = d.build_layer()
                    # tie the shared weight to the first occurrence
                    setattr(layer, d.shared_weight_attr,
                            getattr(src, d.shared_weight_attr))
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                built.append((layer, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, None))
            else:
                raise TypeError(f"bad pipeline layer desc {d!r}")
        self.run_functions = built
        reg = LayerList([l for l, _ in built if isinstance(l, Layer)])
        self._layers_list = reg
        # stage boundaries: uniform split
        n = len(built)
        per = int(np.ceil(n / self._num_stages))
        self._stage_bounds = [(i * per, min((i + 1) * per, n))
                              for i in range(self._num_stages)]

    def get_stage_from_index(self, idx):
        for s, (a, b) in enumerate(self._stage_bounds):
            if a <= idx < b:
                return s
        return self._num_stages - 1

    def forward(self, x):
        from ..distributed.fleet.recompute import recompute
        for i, (layer, ffn) in enumerate(self.run_functions):
            fn = ffn if ffn is not None else layer
            if self._recompute_interval and isinstance(layer, Layer) and \
                    i % self._recompute_interval == 0 and self.training:
                x = recompute(fn, x) if ffn is None else recompute(lambda v: ffn(layer, v), x)
            else:
                x = fn(x) if ffn is None else ffn(layer, x)
        return x

    def pin_stages(self, mesh, axis_name="pp"):
        """Place each stage's params on its slice of the pp axis."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
        names = list(jmesh.axis_names)
        if axis_name not in names:
            return self
        axis = names.index(axis_name)
        devs = np.moveaxis(jmesh.devices, axis, 0)
        for s, (a, b) in enumerate(self._stage_bounds):
            stage_devs = devs[s].reshape(-1)
            for layer, _ in self.run_functions[a:b]:
                if isinstance(layer, Layer):
                    for p in layer.parameters():
                        p._buf = jax.device_put(p._buf, stage_devs[0])
        return self


class PipelineParallel(Layer):
    """reference pipeline_parallel.py:255; train_batch:820."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = getattr(strategy, "pipeline_configs", {}) if strategy else {}
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", None)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _split_micro(self, data, n):
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d, n) for d in data]
            return list(zip(*parts))
        return ops.split(data, n, axis=0)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None,
                    loss_fn=None):
        """F-then-B microbatch schedule with grad accumulation on the tape."""
        self.train()
        inputs, labels = data
        n = self.accumulate_steps
        micro_x = self._split_micro(inputs, n)
        micro_y = self._split_micro(labels, n)
        total = None
        losses = []
        for x, y in zip(micro_x, micro_y):
            out = self._layers(x)
            lf = loss_fn or getattr(self._layers, "_loss_fn", None)
            loss = lf(out, y) if lf is not None else out
            loss = loss / n
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()
            losses.append(loss)
            total = loss if total is None else total + loss.detach()
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        acc = losses[0].detach()
        for l in losses[1:]:
            acc = acc + l.detach()
        return acc

    def eval_batch(self, data, compute_loss=True):
        self.eval()
        inputs, labels = data
        out = self._layers(inputs)
        lf = getattr(self._layers, "_loss_fn", None)
        if compute_loss and lf is not None:
            return lf(out, labels)
        return out

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved/VPP schedule (reference :1179) — numerics identical; the
    virtual-stage ordering is a memory/overlap optimization the XLA scheduler
    performs on the captured program."""
