"""PipelineLayer container + PipelineParallel 1F1B schedule (reference:
fleet/meta_parallel/parallel_layers/pp_layers.py:258 PipelineLayer,
fleet/meta_parallel/pipeline_parallel.py:255 PipelineParallel, 1F1B
forward_backward_pipeline:575, interleave PipelineParallelWithInterleave:1179).

TPU-native realization. The reference drives per-rank schedules over NCCL p2p;
on a single-controller TPU mesh every stage's program is issued from one host,
so the schedule is a *global interleaving* of per-stage forward/backward ops.
What the schedule controls is the same thing it controls on GPU: how many
microbatches are live at once (peak activation memory) and the op ordering XLA
sees. Stage boundaries are realized as tape detach points: each stage's
forward starts from a fresh leaf tensor, so its backward can run independently
given the output cotangent — exactly the reference's p2p activation/grad
hand-off, with PJRT device-to-device copies instead of NCCL send/recv.

Schedules:
  * FThenB (GPipe)   — all M forwards, then all M backwards; M live microbatches.
  * 1F1B             — warmup of (num_stages-1) forwards, then steady-state
                       one-forward-one-backward, then drain; at most
                       `num_stages` live microbatches regardless of M.
  * interleave (VPP) — layers split into num_stages × V chunks assigned
                       round-robin (stage s owns chunks s, s+P, s+2P, …);
                       1F1B at chunk granularity.

The homogeneous stacked-stage SPMD fast path (shard_map + ppermute) lives in
pipeline.py; this module is the generic heterogeneous-stage container.
"""
from __future__ import annotations

from collections import deque

import numpy as np
import jax

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..nn.layer.container import LayerList
from .. import ops


class LayerDesc:
    """Lazy layer description (reference pp_layers.py LayerDesc)."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-shared layer across stages (reference pp_layers.py SharedLayerDesc).
    On a single-controller mesh the same Parameter object is simply reused."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


def _segment_uniform(n_items, n_parts):
    """Even split of n_items into n_parts contiguous bounds."""
    per = int(np.ceil(n_items / n_parts)) if n_items else 0
    return [(min(i * per, n_items), min((i + 1) * per, n_items))
            for i in range(n_parts)]


class PipelineLayer(Layer):
    """Stage-partitioned layer container.

    seg_method:
      * "uniform"            — split the raw layer list evenly.
      * "layer:ClassName"    — count only layers of that class when balancing
                               (reference SegmentLayers with method
                               "layer:TransformerBlock"); leading non-matching
                               layers (embedding) join the first chunk, trailing
                               ones (final norm / head) join the last.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        self._num_stages = num_stages or (topology.get_dim("pp") if topology else 1)
        self._num_virtual = num_virtual_pipeline_stages or 1
        self._recompute_interval = recompute_interval
        self._seg_method = seg_method
        descs = list(layers)
        built = []
        self._shared = {}
        for d in descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    src = self._shared[d.layer_name]
                    layer = d.build_layer()
                    # tie the shared weight to the first occurrence
                    setattr(layer, d.shared_weight_attr,
                            getattr(src, d.shared_weight_attr))
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                built.append((layer, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, None))
            else:
                raise TypeError(f"bad pipeline layer desc {d!r}")
        self.run_functions = built
        reg = LayerList([l for l, _ in built if isinstance(l, Layer)])
        self._layers_list = reg
        self._chunk_bounds = self._segment(self._num_stages * self._num_virtual)
        self._pin_exempt = set()   # ids of params shared across stages (tied)

    # ---- partitioning --------------------------------------------------------
    def _segment(self, n_parts):
        n = len(self.run_functions)
        m = self._seg_method
        if isinstance(m, str) and m.startswith("layer:"):
            cls_name = m.split(":", 1)[1]
            idxs = [i for i, (l, _) in enumerate(self.run_functions)
                    if type(l).__name__ == cls_name]
            if not idxs:
                return _segment_uniform(n, n_parts)
            if len(idxs) % n_parts != 0:
                raise ValueError(
                    f"cannot split {len(idxs)} {cls_name} layers into "
                    f"{n_parts} equal pipeline chunks")
            per = len(idxs) // n_parts
            bounds = []
            for p in range(n_parts):
                a = 0 if p == 0 else idxs[p * per]
                b = n if p == n_parts - 1 else idxs[(p + 1) * per]
                bounds.append((a, b))
            return bounds
        return _segment_uniform(n, n_parts)

    @property
    def num_chunks(self):
        return len(self._chunk_bounds)

    def stage_of_chunk(self, c):
        """Round-robin virtual-stage assignment: chunk c lives on stage c % P
        (reference interleave get_model_chunk_id inverse)."""
        return c % self._num_stages

    def get_stage_from_index(self, idx):
        for c, (a, b) in enumerate(self._chunk_bounds):
            if a <= idx < b:
                return self.stage_of_chunk(c)
        return self._num_stages - 1

    # ---- execution -----------------------------------------------------------
    def _run_segment(self, a, b, x):
        from ..distributed.fleet.recompute import recompute
        for i in range(a, b):
            layer, ffn = self.run_functions[i]
            fn = ffn if ffn is not None else layer
            if self._recompute_interval and isinstance(layer, Layer) and \
                    i % self._recompute_interval == 0 and self.training:
                x = recompute(fn, x) if ffn is None else \
                    recompute(lambda v: ffn(layer, v), x)
            else:
                x = fn(x) if ffn is None else ffn(layer, x)
        return x

    def forward_chunk(self, c, x):
        a, b = self._chunk_bounds[c]
        return self._run_segment(a, b, x)

    def forward(self, x):
        return self._run_segment(0, len(self.run_functions), x)

    def chunk_parameters(self, c):
        a, b = self._chunk_bounds[c]
        out = []
        for layer, _ in self.run_functions[a:b]:
            if isinstance(layer, Layer):
                out.extend(layer.parameters())
        return out

    def pin_stages(self, mesh, axis_name="pp"):
        """Place each chunk's params on its stage's slice of the pp axis.
        With VPP the round-robin assignment means stage s hosts V
        non-contiguous chunks — the same placement the reference's interleave
        partitioner produces (pp_layers.py _segment_network_for_interleave)."""
        jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
        names = list(jmesh.axis_names)
        if axis_name not in names:
            return self
        axis = names.index(axis_name)
        devs = np.moveaxis(jmesh.devices, axis, 0)
        # params shared across chunks (tied embeddings, SharedLayerDesc) stay
        # uncommitted so every consuming stage can read them — the reference
        # instead allreduces tied-weight grads across the pp group
        counts = {}
        for c in range(self.num_chunks):
            for p in self.chunk_parameters(c):
                counts[id(p)] = counts.get(id(p), 0) + 1
        shared = {k for k, v in counts.items() if v > 1} | self._pin_exempt
        self._chunk_device = {}
        for c in range(self.num_chunks):
            stage_devs = np.asarray(devs[self.stage_of_chunk(c)]).reshape(-1)
            self._chunk_device[c] = stage_devs[0]
            for p in self.chunk_parameters(c):
                if id(p) not in shared:
                    p._buf = jax.device_put(p._buf, stage_devs[0])
        return self


def _is_float_tensor(t):
    import jax.numpy as jnp
    return isinstance(t, Tensor) and jnp.issubdtype(t._data.dtype, jnp.floating)


def _as_leaf(t, device=None):
    """Detach into a fresh grad-requiring leaf — the tape-level stage boundary
    (the reference's p2p recv of the activation). When stages are pinned,
    `device` hops the activation onto the consuming stage's device (the
    device-to-device copy NCCL send/recv performs on GPU)."""
    if not _is_float_tensor(t):
        return t
    buf = t._data if device is None else jax.device_put(t._data, device)
    leaf = Tensor(buf, stop_gradient=False)
    return leaf


def _as_leaf_struct(struct, device=None):
    """Boundary detach over a flat tuple/list stream (stages may hand off
    several tensors — e.g. hidden state + carried MoE aux loss — matching the
    reference's tuple p2p payloads)."""
    if isinstance(struct, (tuple, list)):
        return type(struct)(_as_leaf(t, device) for t in struct)
    return _as_leaf(struct, device)


def _boundary_leaves(struct):
    """Float-Tensor members of a boundary structure, positionally ordered."""
    if isinstance(struct, (tuple, list)):
        return [t for t in struct if _is_float_tensor(t)]
    return [struct] if _is_float_tensor(struct) else []


def _hop_cot(g, like):
    """Move a boundary cotangent onto the producing stage's device."""
    try:
        dev = like._data.device
    except Exception:
        return g
    return Tensor(jax.device_put(g._data, dev), stop_gradient=True)


class PipelineParallel(Layer):
    """1F1B microbatch schedule (reference pipeline_parallel.py:255,
    forward_backward_pipeline:575 — warmup / steady 1F1B / drain)."""

    schedule_mode = "1F1B"

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = getattr(strategy, "pipeline_configs", {}) if strategy else {}
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", None)
        self.max_in_flight = 0       # schedule introspection (tests assert this)

    @property
    def num_stages(self):
        return getattr(self._layers, "_num_stages", 1)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _split_micro(self, data, n):
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d, n) for d in data]
            return list(zip(*parts))
        return ops.split(data, n, axis=0)

    # ---- per-microbatch stage-wise fwd/bwd ----------------------------------
    def _forward_micro(self, x, y, loss_fn, n_micro):
        """Forward one microbatch chunk-by-chunk with detach boundaries.
        Returns (boundaries, loss): boundaries[c] = (leaf_in, out) per chunk."""
        pl = self._layers
        n_chunks = getattr(pl, "num_chunks", None)
        dev_of = getattr(pl, "_chunk_device", None) or {}
        boundaries = []
        h = x
        if n_chunks is None:          # plain Layer: single stage
            out = pl(h)
            boundaries.append((h, out))
            h = out
        else:
            for c in range(n_chunks):
                leaf = _as_leaf_struct(h, device=dev_of.get(c)) if c > 0 else h
                out = pl.forward_chunk(c, leaf)
                boundaries.append((leaf, out))
                h = out
        lf = loss_fn or getattr(pl, "_loss_fn", None)
        loss = lf(h, y) if lf is not None else h
        loss = loss / n_micro
        return boundaries, loss

    def _backward_micro(self, boundaries, loss, scaler=None, param_ids=None):
        """Backward chunk-by-chunk in reverse — each chunk's tape sweep is
        independent because its input is a detached leaf; the cotangent hops
        the boundary exactly like the reference's p2p grad send.

        param_ids (zero-bubble): defer these leaf params' weight grads;
        returns the deferred W closures (empty list when param_ids is None)."""
        from ..autograd.backward import backward as _backward
        pinned = bool(getattr(self._layers, "_chunk_device", None))
        kw = {"defer_param_ids": param_ids} if param_ids else {}
        deferred = []
        cots = None          # aligned with _boundary_leaves of chunk c's output
        for c in reversed(range(len(boundaries))):
            leaf_struct, out_struct = boundaries[c]
            if c == len(boundaries) - 1:
                l = scaler.scale(loss) if scaler is not None else loss
                res = _backward([l], [None], **kw)
            else:
                outs = _boundary_leaves(out_struct)
                pairs = [(o, g) for o, g in zip(outs, cots) if g is not None]
                if not pairs:
                    raise RuntimeError(
                        f"pipeline chunk {c + 1} produced no input gradient")
                res = _backward([o for o, _ in pairs], [g for _, g in pairs],
                                **kw)
            if param_ids and res:
                deferred.extend(res)
            if c > 0:
                leaves = _boundary_leaves(leaf_struct)
                prev_outs = _boundary_leaves(boundaries[c - 1][1])
                cots = []
                for leaf, po in zip(leaves, prev_outs):
                    g = leaf.grad
                    leaf.grad = None
                    if g is not None and pinned:
                        g = _hop_cot(g, po)
                    cots.append(g)
        return deferred

    # ---- schedules -----------------------------------------------------------
    def _train_batch_impl(self, data, optimizer, lr_scheduler, scaler, loss_fn,
                          param_ids=None):
        """Shared 1F1B loop: warmup (P-1) forwards, steady one-fwd-one-bwd,
        drain. Peak live microbatches = min(P, M) — the 1F1B memory bound — vs
        GPipe's M (reference forward_backward_pipeline:575).

        With param_ids set (ZB-H1), each backward is B-only; its deferred dW
        closures queue per-microbatch, and the queue is drained FIFO whenever
        it exceeds the P-microbatch window — so W work fills the bubble right
        after the stage's critical-path B's, and residual memory stays within
        the ZB-H1 bound instead of growing O(accumulate_steps)."""
        self.train()
        inputs, labels = data
        n = self.accumulate_steps
        micro_x = self._split_micro(inputs, n)
        micro_y = self._split_micro(labels, n)
        P = self.num_stages
        in_flight = deque()
        w_queue = deque()                     # per-microbatch deferred-W lists
        self.max_in_flight = 0
        self.w_deferred_total = 0

        def run_oldest_w():
            for w in w_queue.popleft():
                w()

        total = None
        for m in range(n):
            boundaries, loss = self._forward_micro(micro_x[m], micro_y[m],
                                                   loss_fn, n)
            d = loss.detach()
            total = d if total is None else total + d
            in_flight.append((boundaries, loss))
            self.max_in_flight = max(self.max_in_flight, len(in_flight))
            if len(in_flight) >= P:           # steady state: 1F1B
                b, l = in_flight.popleft()
                ws = self._backward_micro(b, l, scaler=scaler,
                                          param_ids=param_ids)
                if ws:
                    w_queue.append(ws)
                    self.w_deferred_total += len(ws)
                while len(w_queue) > P:       # ZB-H1 residual window
                    run_oldest_w()
        while in_flight:                      # drain: B's are the critical path
            b, l = in_flight.popleft()
            ws = self._backward_micro(b, l, scaler=scaler, param_ids=param_ids)
            if ws:
                w_queue.append(ws)
                self.w_deferred_total += len(ws)
        while w_queue:                        # bubble fill: remaining dW
            run_oldest_w()
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None,
                    loss_fn=None):
        return self._train_batch_impl(data, optimizer, lr_scheduler, scaler,
                                      loss_fn)

    def eval_batch(self, data, compute_loss=True):
        self.eval()
        inputs, labels = data
        out = self._layers(inputs)
        lf = getattr(self._layers, "_loss_fn", None)
        if compute_loss and lf is not None:
            return lf(out, labels)
        return out

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)


class ZeroBubblePipelineParallel(PipelineParallel):
    """Zero-bubble (ZB-H1) schedule (reference: distributed/passes/
    pipeline_scheduler_pass/pipeline_zero_bubble.py).

    The reference splits each backward op into B (grad-input, on the critical
    path — the upstream stage waits for it) and W (grad-weight, off the
    critical path) and sinks W into the drain-phase bubble, eliminating the
    tail bubble of 1F1B. Here the split happens at the tape level:
    ``backward_split`` propagates activation cotangents immediately and
    returns deferred W closures, which this schedule runs only during the
    drain — so each stage's device queue sees F/B work first and fills its
    idle tail with dW, exactly the ZB-H1 op ordering.

    Numerics are identical to 1F1B (same grads, different order); the
    deferred-W residuals are drained on a P-microbatch window so peak memory
    stays within the ZB-H1 bound (1F1B activations + one window of dW
    residuals), not O(accumulate_steps)."""

    schedule_mode = "ZB-H1"

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None,
                    loss_fn=None):
        param_ids = {id(p) for p in self._layers.parameters()}
        return self._train_batch_impl(data, optimizer, lr_scheduler, scaler,
                                      loss_fn, param_ids=param_ids)


def interleave_schedule(num_micro, num_stages, num_virtual, rank):
    """Per-rank interleaved-1F1B op list: [('F'|'B', microbatch, chunk), ...]
    (reference PipelineParallelWithInterleave:1179 / Megatron interleaving).

    Forward-op k on rank r touches chunk ((k % (P*V)) // P) of microbatch
    ((k // (P*V)) * P + k % P); warmup covers (P - r - 1) * 2 + (V - 1) * P
    forward ops, then steady state alternates 1F1B, then drain.
    Used for introspection/verification of the global executed order.
    """
    P, V, M = num_stages, num_virtual, num_micro
    if M % P != 0:
        raise ValueError("interleave requires microbatches % stages == 0")
    total = M * V

    def fwd_k(k):
        grp = k // (P * V)
        chunk = (k % (P * V)) // P
        micro = grp * P + k % P
        return ("F", micro, chunk)

    def bwd_k(k):
        grp = k // (P * V)
        chunk = V - 1 - (k % (P * V)) // P
        micro = grp * P + k % P
        return ("B", micro, chunk)

    warmup = min((P - rank - 1) * 2 + (V - 1) * P, total)
    sched = [fwd_k(k) for k in range(warmup)]
    for k in range(warmup, total):
        sched.append(fwd_k(k))
        sched.append(bwd_k(k - warmup))
    sched.extend(bwd_k(k) for k in range(total - warmup, total))
    return sched


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved (virtual-stage) 1F1B (reference :1179).

    The container must be built with num_virtual_pipeline_stages=V; chunks are
    assigned round-robin so stage s hosts chunks s, s+P, … Execution runs the
    chunk-granular schedule: warmup forwards per the interleave depth, then
    one-chunk-forward/one-chunk-backward, then drain. Numerics are identical
    to 1F1B; what changes is chunk placement + op order (bubble shrinks by V)."""

    schedule_mode = "interleave"

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__(layers, hcg=hcg, strategy=strategy)
        if getattr(layers, "_num_virtual", 1) < 2:
            raise ValueError(
                "PipelineParallelWithInterleave needs a PipelineLayer built "
                "with num_virtual_pipeline_stages >= 2")

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None,
                    loss_fn=None):
        self.train()
        inputs, labels = data
        n = self.accumulate_steps
        P = self.num_stages
        V = self._layers._num_virtual
        if n % P != 0:
            raise ValueError(
                f"interleave schedule needs accumulate_steps ({n}) divisible "
                f"by num_stages ({P})")
        micro_x = self._split_micro(inputs, n)
        micro_y = self._split_micro(labels, n)

        # chunk-granular state per microbatch
        G = self._layers.num_chunks                     # global chunks = P * V
        acts = [[None] * G for _ in range(n)]           # (leaf, out) per chunk
        losses = [None] * n
        cots = [None] * n                               # boundary cotangent
        self.max_in_flight = 0
        live = set()

        dev_of = getattr(self._layers, "_chunk_device", None) or {}

        def fwd_chunk(m, g):
            h = micro_x[m] if g == 0 else acts[m][g - 1][1]
            leaf = _as_leaf_struct(h, device=dev_of.get(g)) if g > 0 else h
            out = self._layers.forward_chunk(g, leaf)
            acts[m][g] = (leaf, out)
            if g + 1 == G:
                lf = loss_fn or getattr(self._layers, "_loss_fn", None)
                losses[m] = (lf(out, micro_y[m]) if lf is not None else out) / n
            live.add(m)
            self.max_in_flight = max(self.max_in_flight, len(live))

        def bwd_chunk(m, g):
            from ..autograd.backward import backward as _backward
            leaf_struct, out_struct = acts[m][g]
            if g == G - 1:
                l = scaler.scale(losses[m]) if scaler is not None else losses[m]
                _backward([l], [None])
            else:
                outs = _boundary_leaves(out_struct)
                pairs = [(o, c) for o, c in zip(outs, cots[m]) if c is not None]
                if not pairs:
                    raise RuntimeError(
                        f"pipeline chunk {g + 1} produced no input gradient")
                _backward([o for o, _ in pairs], [c for _, c in pairs])
            if g > 0:
                leaves = _boundary_leaves(leaf_struct)
                prev_outs = _boundary_leaves(acts[m][g - 1][1])
                gs = []
                for leaf, po in zip(leaves, prev_outs):
                    cg = leaf.grad
                    leaf.grad = None
                    if cg is not None and dev_of:
                        cg = _hop_cot(cg, po)
                    gs.append(cg)
                cots[m] = gs
            acts[m][g] = None
            if g == 0:
                live.discard(m)

        # Merge every rank's interleave schedule into one dependency-ordered
        # global execution (the single-controller realization of the per-rank
        # p2p-synchronized schedules). Rank r owns global chunks v*P + r.
        rank_ops = [deque(interleave_schedule(n, P, V, r)) for r in range(P)]
        done_f, done_b = set(), set()

        def runnable(op, r):
            kind, m, v = op
            g = v * P + r
            if kind == "F":
                return g == 0 or (m, g - 1) in done_f
            if (m, g) not in done_f:
                return False
            return g == G - 1 or (m, g + 1) in done_b

        while any(rank_ops):
            progress = False
            for r in range(P):
                while rank_ops[r] and runnable(rank_ops[r][0], r):
                    kind, m, v = rank_ops[r].popleft()
                    g = v * P + r
                    if kind == "F":
                        fwd_chunk(m, g)
                        done_f.add((m, g))
                    else:
                        bwd_chunk(m, g)
                        done_b.add((m, g))
                    progress = True
            if not progress:
                raise RuntimeError("interleave schedule deadlocked")

        total = None
        for m in range(n):
            d = losses[m].detach()
            total = d if total is None else total + d
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total
