"""Megatron sequence parallelism (reference: python/paddle/distributed/fleet/
utils/sequence_parallel_utils.py — ColumnSequenceParallelLinear:429,
RowSequenceParallelLinear:564, AllGatherOp:111, ReduceScatterOp:127).

TPU-native: SP is a sharding choice — activations carry Shard(seq_dim) on the
'mp' axis outside the matmul blocks; GSPMD turns the boundary reshards into the
all-gather / reduce-scatter pair the reference codes by hand.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..core.dispatch import apply_op
from ..nn.layer.layers import Layer
from ..nn.initializer import XavierUniform
from ..nn import functional as F
from .mp_layers import _mp_mesh, _shard_param, _constrain


def _seq_spec(ndim, seq_axis=1):
    entries = [None] * ndim
    entries[seq_axis] = "mp"
    return P(*entries)


class AllGatherOp(Layer):
    """seq-sharded -> replicated (reference :111)."""

    def forward(self, x):
        return _constrain(x, P())


class ReduceScatterOp(Layer):
    """partial/replicated -> seq-sharded (reference :127)."""

    def forward(self, x):
        return _constrain(x, _seq_spec(x.ndim))


def scatter(x, seq_axis=1):
    return _constrain(x, _seq_spec(x.ndim, seq_axis))


def all_gather(x):
    return _constrain(x, P())


class ColumnSequenceParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr,
                                            default_initializer=XavierUniform())
        _shard_param(self.weight, P(None, "mp"))
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None
        if self.bias is not None:
            _shard_param(self.bias, P("mp"))

    def forward(self, x):
        # input arrives seq-sharded; GSPMD emits the all-gather before the matmul
        y = F.linear(x, self.weight, self.bias)
        return _constrain(y, P(*([None] * (y.ndim - 1) + ["mp"])))


class RowSequenceParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=True, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr,
                                            default_initializer=XavierUniform())
        _shard_param(self.weight, P("mp", None))
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        # partial-sum output reduce-scatters onto the seq dim
        return _constrain(y, _seq_spec(y.ndim))


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter):
    return getattr(parameter, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """reference :192 — under GSPMD the grad reduction for SP params is emitted
    by the partitioner; nothing to hook."""
    return model
