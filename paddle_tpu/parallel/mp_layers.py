"""Tensor-parallel (Megatron) layers (reference: python/paddle/distributed/fleet/
layers/mpu/mp_layers.py — VocabParallelEmbedding:49, ColumnParallelLinear:336,
RowParallelLinear:543, ParallelCrossEntropy:744; comm ops mp_ops.py).

TPU-native: instead of explicit c_identity/mp_allreduce calls, each layer holds
params device_put with a NamedSharding over the 'mp' mesh axis and constrains its
activations; XLA GSPMD inserts the all-reduce/all-gather on ICI. The layer API
(gather_output, input_is_parallel, ...) is preserved.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap
from ..nn.layer.layers import Layer
from ..nn.initializer import XavierUniform, Constant
from ..nn import functional as F
from ..distributed.fleet.topology import get_hybrid_communicate_group


def _mp_mesh():
    hcg = get_hybrid_communicate_group()
    return hcg.get_mesh().jax_mesh()


def _shard_param(p: Tensor, spec: P):
    mesh = _mp_mesh()
    if np.prod(mesh.devices.shape) == 1:
        return p
    # replicate dims that don't divide evenly across their mesh axes
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    entries = list(spec) + [None] * (p.ndim - len(tuple(spec)))
    for d, e in enumerate(entries):
        if e is None:
            continue
        axes = e if isinstance(e, tuple) else (e,)
        n = int(np.prod([sizes[a] for a in axes]))
        if p._buf.shape[d] % n != 0:
            entries[d] = None
    p._data = jax.device_put(p._buf, NamedSharding(mesh, P(*entries)))
    return p


def _constrain(x, spec: P):
    mesh = _mp_mesh()
    if np.prod(mesh.devices.shape) == 1:
        return x
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    entries = list(spec) + [None] * (x.ndim - len(tuple(spec)))
    for d, e in enumerate(entries):
        if e is None:
            continue
        axes = e if isinstance(e, tuple) else (e,)
        n = int(np.prod([sizes[a] for a in axes]))
        if x.shape[d] % n != 0:
            entries[d] = None
    return apply_op("sharding_constraint",
                    lambda a: jax.lax.with_sharding_constraint(
                        a, NamedSharding(mesh, P(*entries))), x)


class VocabParallelEmbedding(Layer):
    """Vocab dim sharded over mp; out-of-shard lookups resolve via GSPMD gather
    (the reference masks + allreduces explicitly, mp_layers.py:49)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None,
                 name=None):
        super().__init__()
        self._num_embeddings, self._embedding_dim = num_embeddings, embedding_dim
        self.weight = self.create_parameter([num_embeddings, embedding_dim],
                                            attr=weight_attr,
                                            default_initializer=XavierUniform())
        _shard_param(self.weight, P("mp", None))

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """W [in, out] sharded on out (mp); y local-sharded unless gather_output."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._gather_output = gather_output
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr,
                                            default_initializer=XavierUniform())
        _shard_param(self.weight, P(None, "mp"))
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            _shard_param(self.bias, P("mp"))

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self._gather_output:
            return _constrain(y, P())  # gather shards -> replicated
        return _constrain(y, P(*([None] * (y.ndim - 1) + ["mp"])))


class RowParallelLinear(Layer):
    """W [in, out] sharded on in (mp); partial sums all-reduced by GSPMD."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self._input_is_parallel = input_is_parallel
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr,
                                            default_initializer=XavierUniform())
        _shard_param(self.weight, P("mp", None))
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)

    def forward(self, x):
        if self._input_is_parallel:
            x = _constrain(x, P(*([None] * (x.ndim - 1) + ["mp"])))
        y = F.linear(x, self.weight, self.bias)
        return _constrain(y, P())


class ParallelCrossEntropy(Layer):
    """reference mp_layers.py:744 (c_softmax_with_cross_entropy over the
    vocab shard).

    The logits stay VOCAB-SHARDED end to end: the stable log-sum-exp's max
    and sum reductions over the sharded axis lower to psums on ICI, and the
    label term is a one-hot contraction (shard-local multiply + the same
    reduction) rather than a gather — so no [B, S, V] replicated tensor is
    ever materialized (the reference's c_softmax_with_cross_entropy does the
    identical two-allreduce dance by hand)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self._ignore_index = ignore_index

    def forward(self, input, label):
        ignore = self._ignore_index
        input = _constrain(
            input, P(*([None] * (input.ndim - 1) + ["mp"])))
        return apply_op("parallel_cross_entropy",
                        lambda x, y: _pce_math(x, y, ignore), input, label)


def _pce_math(x, y, ignore=-100):
    """The shard-local CE math (module-level so tests can lower THIS exact
    function with sharded inputs and assert the compiled program never
    all-gathers the vocab axis)."""
    xf = x.astype(jnp.float32)
    m = jnp.max(xf, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(xf - m), axis=-1, keepdims=True)) + m
    oh = jax.nn.one_hot(y, x.shape[-1], dtype=xf.dtype)
    picked = jnp.sum(xf * oh, axis=-1)
    loss = lse[..., 0] - picked
    if ignore is not None:
        loss = jnp.where(y == ignore, 0.0, loss)
    return loss


class RNGStatesTracker:
    """TP-aware RNG (reference: fleet/layers/mpu/random.py:34).

    Under the single-controller GSPMD model, one global key already yields
    identical masks on every shard of replicated activations and distinct
    per-position randomness on sharded ones — so the tracker only needs to
    provide named alternate streams.
    """

    def __init__(self):
        self._states = {}

    def add(self, name, seed):
        from ..core.rng import Generator
        if name in self._states:
            raise ValueError(f"rng state {name} already exists")
        self._states[name] = Generator(seed)

    def get_states_tracker(self):
        return dict(self._states)

    def set_states_tracker(self, states):
        self._states = dict(states)

    def rng_state(self, name="model_parallel_rng"):
        import contextlib

        @contextlib.contextmanager
        def cm():
            from ..core import rng as rng_mod
            if name not in self._states:
                self.add(name, np.random.randint(0, 2 ** 31 - 1))
            prev = rng_mod._default_generator
            rng_mod._default_generator = self._states[name]
            try:
                yield
            finally:
                rng_mod._default_generator = prev
        return cm()


_RNG_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_TRACKER


def model_parallel_random_seed(seed=None):
    import random as pyrandom
    global _RNG_TRACKER
    _RNG_TRACKER = RNGStatesTracker()
    basic = seed if seed is not None else pyrandom.randint(0, 2 ** 30)
    from ..core.rng import seed as set_seed
    set_seed(basic)
    _RNG_TRACKER.add("model_parallel_rng", basic + 1024)
