"""Version-portable ``shard_map`` import — the ONE place the jax version
split lives (previously copy-pasted into every parallel layer).

jax >= 0.4.35 exposes ``jax.shard_map`` with a ``check_vma`` kwarg; older
releases only have ``jax.experimental.shard_map.shard_map``, which spells the
same knob ``check_rep``.  Callers always write the new spelling
(``check_vma=...``); the shim translates when running on the experimental
namespace.

This module is also the canonical symbol the graftlint
``sharding-spec-coverage`` pass resolves: importing ``shard_map`` from here
(rather than re-declaring the fallback) is what lets the analyzer see every
call site.
"""
from __future__ import annotations

try:                                     # jax >= 0.4.35 top-level home
    from jax import shard_map
except ImportError:                      # older jax: experimental namespace,
    from jax.experimental.shard_map import shard_map as _shard_map_experimental

    def shard_map(f, **kw):              # ...which spells check_vma check_rep
        kw["check_rep"] = kw.pop("check_vma", True)
        return _shard_map_experimental(f, **kw)

__all__ = ["shard_map"]
