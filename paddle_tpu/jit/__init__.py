"""paddle.jit — to_static capture + save/load export (reference:
python/paddle/jit/api.py — to_static:197, save:956, load:1527).

Export format: jax.export serialized StableHLO (portable, version-stamped) +
pickled params — the PIR-serialization analog (SURVEY §2.2). A loaded artifact
is a TranslatedLayer-style predictor.
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from .to_static import (to_static, not_to_static, StaticFunction,  # noqa: F401
                        scan_steps, ScanStaticFunction)
from ..core.tensor import Tensor
from ..core.dispatch import unwrap

ignore_module = lambda *a, **k: None  # noqa: E731 — SOT-only concept


def enable_to_static(flag=True):
    pass


class InputSpec:
    """reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        from ..core.dtype import convert_dtype
        self.shape = list(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def _example_from_spec(spec: InputSpec):
    shape = [1 if (s is None or s < 0) else s for s in (spec.shape or [1])]
    return Tensor(jnp.zeros(shape, spec.dtype))


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — export forward as serialized StableHLO + params."""
    from ..nn.layer.layers import Layer
    from jax import export as jexport

    if isinstance(layer, Layer):
        fwd = layer.forward
        fn = fwd.function if isinstance(fwd, StaticFunction) else fwd
        params = {k: np.asarray(unwrap(v)) for k, v in layer.state_dict().items()}
        layer.eval()
        names = list(layer.state_dict().keys())
        tensors = [layer.state_dict()[k] for k in names]

        def pure(param_arrays, *input_arrays):
            # bind params by temporarily swapping buffers
            saved = [t._buf for t in tensors]
            for t, a in zip(tensors, param_arrays):
                t._buf = a
            try:
                ins = [Tensor(a) for a in input_arrays]
                out = fn(*ins)
            finally:
                for t, s in zip(tensors, saved):
                    t._buf = s
            leaves = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))[0]
            return tuple(l._data if isinstance(l, Tensor) else l for l in leaves)
    else:
        fn = layer.function if isinstance(layer, StaticFunction) else layer
        params = {}
        tensors, names = [], []

        def pure(param_arrays, *input_arrays):
            ins = [Tensor(a) for a in input_arrays]
            out = fn(*ins)
            leaves = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))[0]
            return tuple(l._data if isinstance(l, Tensor) else l for l in leaves)

    if input_spec is None:
        raise ValueError("jit.save requires input_spec (shape/dtype of inputs)")
    # None/-1 dims export as jax.export symbolic dimensions, so the loaded
    # program accepts any batch size (reference programs have -1 dims too).
    # All dims must share ONE symbolic scope, so collect names first and make
    # a single symbolic_shape call.
    def _dyn(d):
        return d is None or (isinstance(d, int) and d < 0)

    # a dynamic LEADING dim is the batch and shares one symbol across all
    # inputs (they must agree at call time — reference models batch this
    # way); other dynamic dims get independent symbols
    def _sym_name(i, j):
        return "b" if j == 0 else f"d{i}_{j}"

    dyn_names = sorted({_sym_name(i, j) for i, s in enumerate(input_spec)
                        if isinstance(s, InputSpec) and s.shape is not None
                        for j, d in enumerate(s.shape) if _dyn(d)})
    syms = dict(zip(dyn_names, jexport.symbolic_shape(
        ", ".join(dyn_names)))) if dyn_names else {}
    examples = []
    for i, s in enumerate(input_spec):
        if isinstance(s, InputSpec) and s.shape is not None and any(
                _dyn(d) for d in s.shape):
            dims = tuple(syms[_sym_name(i, j)] if _dyn(d) else int(d)
                         for j, d in enumerate(s.shape))
            examples.append(jax.ShapeDtypeStruct(dims, np.dtype(s.dtype)))
        else:
            examples.append((_example_from_spec(s)
                             if isinstance(s, InputSpec) else s)._data)
    param_arrays = [np.asarray(unwrap(t)) for t in tensors]
    exported = jexport.export(jax.jit(pure))(param_arrays, *examples)
    blob = exported.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({"params": params, "names": names,
                     "input_spec": [(list(s.shape) if s.shape else None,
                                     np.dtype(s.dtype).name) for s in input_spec]}, f)


class TranslatedLayer:
    """Loaded inference artifact (reference: jit/translated_layer.py)."""

    def __init__(self, exported, params, names):
        self._exported = exported
        self._param_arrays = [jnp.asarray(params[n]) for n in names]

    def __call__(self, *inputs):
        arrays = [unwrap(i) if isinstance(i, Tensor) else jnp.asarray(np.asarray(i))
                  for i in inputs]
        outs = self._exported.call(self._param_arrays, *arrays)
        outs = [Tensor(o) for o in outs]
        return outs[0] if len(outs) == 1 else tuple(outs)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("a jit.load'ed artifact is inference-only")


def load(path, **configs):
    """paddle.jit.load — deserialize the exported program."""
    from jax import export as jexport
    with open(path + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(bytearray(f.read()))
    with open(path + ".pdiparams", "rb") as f:
        meta = pickle.load(f)
    return TranslatedLayer(exported, meta["params"], meta["names"])


_verbosity = 0
_code_level = 0


def set_verbosity(level=0, also_to_stdout=False):
    """reference jit/dy2static/logging_utils.py set_verbosity: controls how
    chatty the capture/transcription pipeline is."""
    global _verbosity
    _verbosity = int(level)
    import logging
    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level else logging.WARNING)


def set_code_level(level=100, also_to_stdout=False):
    """reference jit set_code_level: at >0, to_static prints the captured
    program (the jaxpr of the compiled step) on each compilation."""
    global _code_level
    _code_level = int(level)


def _code_level_value():
    return _code_level
