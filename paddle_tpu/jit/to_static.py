"""Program capture — paddle.jit.to_static (reference: python/paddle/jit/api.py:197
+ the SOT bytecode frontend python/paddle/jit/sot/).

TPU-native redesign: instead of CPython bytecode simulation, capture exploits the
framework's trace-transparent eager core (every op goes through one dispatch
chokepoint; Tensor state reads/writes go through properties):

  call 1 (SPY)    — runs eagerly at full fidelity while recording which external
                    tensors the function READS (params, buffers, optimizer
                    moments, RNG key) and which it WRITES (param update, moment
                    update, key split, .grad assignment).
  call 2+ (REPLAY)— a pure jax function (args, mutated-state, readonly-state) ->
                    (outputs, new-state), jit-compiled with donation of the
                    mutated state buffers; re-runs the SAME python under tracers
                    with shadowed writes. One fused XLA program = fwd + bwd +
                    optimizer step.

Guards: arg treedef + shapes/dtypes + static-arg values (the SOT guard analog) —
a new signature re-traces.

Data-dependent Python control flow (the SOT graph-break case,
reference python/paddle/jit/sot/translate.py:31): a bool()/int() conversion of a
tensor inside the captured fn becomes a VALUE GUARD instead of a break. The spy
records the conversion's concrete value; replay substitutes it (specializing the
trace on that branch) and emits the traced scalar as an extra program output.
At run time the compiled step's guard outputs are checked against the
specialized values — on divergence the step's state writes are discarded, a
variant specialized on the new values is looked up or traced, and the step
re-runs. The whole function stays compiled on every path taken (vs the
reference's SOT, which stitches compiled subgraphs around an eager region).

float() conversions and .numpy() reads — the reference's graph-break case
(python/paddle/jit/sot/translate.py:31) — are STITCHED, not de-compiled
(VERDICT r4 missing #1: `float(loss)` in a metric callback silently marked
the whole train step eager-only forever).  The scheme:

  * capture: a float()/.numpy() read becomes a BREAK EVENT.  The replay trace
    emits the traced value as an extra program output (`break_outs`) and
    substitutes the spy's concrete value so tracing continues.  The trace also
    records the op-dispatch tape (name + output shapes per op).
  * run time: the compiled program runs first (one fused XLA program — the
    matmul region never de-compiles).  Then an ECHO pass re-runs the python
    with every op dispatch short-circuited to shape-only placeholders: zero
    device compute, but the python between breaks (logging, metric appends,
    f-strings) executes with the TRUE per-call values pulled from
    `break_outs`.  State writes commit only after the echo confirms the op
    sequence matched the trace, so a divergence (tensor ops conditioned on a
    broken-out value) rolls back cleanly to one eager call and marks the
    signature eager-only — loudly, never silently wrong.

  Capture-pass semantics: the spy call and each trace pass (abstract trace at
  compile, jit trace on first run, re-spy after a guard divergence) re-run the
  user's python, so side effects fire during capture with CAPTURE-TIME values
  — a metric list may gain one stale duplicate per (re)capture, exactly like
  side effects inside any traced jax.jit function.  Steady state is one echo
  per call with the true value.

  Restriction (documented, checked): a value read at a break must not feed
  back into tensor computation — the trace would have baked the spy-time
  value in.  Feeding it into python-side control flow that CHANGES WHICH OPS
  RUN is detected by the echo tape mismatch; feeding it into an op attribute
  is not detectable and is unsupported (hoist it, or use bool()/int() guards
  which re-specialize).  Side effects before a detected mismatch may run
  twice for that one call (echo, then the eager fallback).  A TENSOR kept
  past the step (``history.append(loss)`` inside the fn, read after it) is a
  shape-only echo placeholder: any later host read raises, pointing here —
  read the value inside the step or return it from the step instead.

Shapes are static per signature; variable seq-len is handled by bucketing
above (SURVEY §7).
"""
from __future__ import annotations

import functools
import logging

import numpy as np
import jax

from ..core.tensor import Tensor
from ..core.dispatch import _state
from .. import observability as _obs

logger = logging.getLogger("paddle_tpu.jit")

_BREAKS = (jax.errors.TracerBoolConversionError,
           jax.errors.ConcretizationTypeError,
           jax.errors.TracerArrayConversionError,
           jax.errors.TracerIntegerConversionError)


class MissedCapture(Exception):
    """Replay/compile saw state the spy pass didn't record. ``permanent=True``
    marks deterministic rejections (e.g. scan_steps restrictions) that re-spying
    can never fix — the signature goes eager-only immediately."""

    def __init__(self, msg, permanent=False):
        super().__init__(msg)
        self.permanent = permanent


class EchoMismatch(Exception):
    """The echo pass diverged from the traced op sequence: the python path
    depends on a float()/.numpy() break value in a way that changes which ops
    run.  The compiled result is untrustworthy for this call — state was NOT
    committed; the caller falls back to eager and pins the signature there."""


_GUARD_KINDS = ("bool", "int")
_BREAK_KINDS = ("float", "numpy")


class EchoPlaceholderTensor(Tensor):
    """Shape-only stand-in the echo pass returns from every short-circuited
    op dispatch (its buffer is a ShapeDtypeStruct, never data). User code
    that smuggles one past the step — ``history.append(loss)`` inside the
    captured fn, read outside it — used to hit an opaque numpy error on a
    ShapeDtypeStruct; any post-echo host read now raises pointing at the
    break-stitching scheme. Inside capture/echo passes reads still route
    through the active trace context like any Tensor."""

    __slots__ = ()

    def _post_echo_error(self):
        return RuntimeError(
            "host read of an echo-pass placeholder Tensor: this value was "
            "produced inside a to_static/scan_steps step and carries no "
            "data outside the call that made it. Read it inside the step "
            "(float()/.numpy() there are stitched breaks) or return it "
            "from the step function — see the break-stitching notes in "
            "paddle_tpu/jit/to_static.py.")

    def numpy(self):
        if _state.trace_ctx is None:
            raise self._post_echo_error()
        return super().numpy()

    def _convert_scalar(self, kind, caster):
        if _state.trace_ctx is None:
            raise self._post_echo_error()
        return super()._convert_scalar(kind, caster)


def _is_tensor(x):
    return isinstance(x, Tensor)


class _SpyContext:
    """Eager pass-through that records external reads + writes + scalar
    events (bool/int guards, float/numpy breaks)."""

    mode = "spy"

    def __init__(self):
        self.reads: dict[int, Tensor] = {}
        self.writes: dict[int, Tensor] = {}
        self.grad_reads: dict[int, Tensor] = {}
        self.grad_writes: dict[int, Tensor] = {}
        self.created: set[int] = set()
        # ordered (kind, concrete value): bool/int -> guards, float/numpy ->
        # breaks; one stream so replay/echo can verify the exact sequence
        self.events: list[tuple[str, object]] = []

    def on_scalar(self, t, kind, caster):
        # read through on_read so a tensor consumed ONLY via bool()/int()/
        # float() is still recorded as an external read (lifted to a program
        # input); otherwise replay would bake the spy-time value in as a
        # constant and the emitted guard/break output could never change
        v = caster(self.on_read(t))
        self.events.append((kind, v))
        return v

    def on_materialize(self, t):
        """Full-array host read (Tensor.numpy()): a break event."""
        arr = np.asarray(self.on_read(t))
        self.events.append(("numpy", arr))
        return arr

    def on_create(self, t):
        self.created.add(id(t))

    def on_read(self, t):
        if id(t) not in self.created:
            self.reads.setdefault(id(t), t)
        return t._buf

    def on_write(self, t, value):
        if id(t) not in self.created:
            self.writes.setdefault(id(t), t)
        t._buf = value

    def on_grad_read(self, t):
        # a pre-existing grad read before any write this step (gradient
        # accumulation with clear_grad outside the captured fn) is external
        # state: record it so replay lifts it to a program input instead of
        # baking the spy pass's concrete grad in as a trace constant
        if (t._grad_buf is not None and id(t) not in self.created
                and id(t) not in self.grad_writes):
            self.grad_reads.setdefault(id(t), t)
        return t._grad_buf

    def on_grad_write(self, t, value):
        if id(t) not in self.created:
            self.grad_writes.setdefault(id(t), t)
        t._grad_buf = value


class _ReplayContext:
    """Pure traced re-execution: reads hit lifted tracers, writes go to shadows."""

    mode = "replay"

    def __init__(self, lifted: dict[int, object], grad_lifted=None,
                 plan=None):
        self.values = lifted                  # id(Tensor) -> traced array
        self.grad_lifted = grad_lifted or {}  # id(Tensor) -> traced grad array
        self.data_shadow: dict[int, object] = {}
        self.grad_shadow: dict[int, object] = {}
        self.plan = plan or []                # [(kind, value)] events from spy
        self.plan_idx = 0
        self.guard_outs: list[object] = []    # traced guard scalars, in order
        self.break_outs: list[object] = []    # traced break values, in order
        self.op_tape: list[tuple] = []        # (name, single, out_meta) per op

    def on_create(self, t):
        pass

    def _next_event(self, kind):
        i = self.plan_idx
        if i >= len(self.plan) or self.plan[i][0] != kind:
            raise MissedCapture(
                "scalar-conversion sequence diverged from the spy pass")
        self.plan_idx += 1
        return self.plan[i][1]

    def on_scalar(self, t, kind, caster):
        import jax.numpy as jnp
        planned = self._next_event(kind)
        val = jnp.asarray(self.on_read(t)).reshape(())
        if kind == "bool":
            # normalize to int32 matching python bool()/int() semantics
            self.guard_outs.append((val != 0).astype(jnp.int32))
        elif kind == "int":
            self.guard_outs.append(val.astype(jnp.int32))  # trunc toward zero
        else:  # float break: ride out in the traced dtype, no equality guard
            # (an f32 round-trip would be observable for f64/int64 tensors
            # under jax_enable_x64 — float() happens host-side in the echo)
            self.break_outs.append(val)
        return planned

    def on_materialize(self, t):
        import jax.numpy as jnp
        planned = self._next_event("numpy")
        self.break_outs.append(jnp.asarray(self.on_read(t)))
        return planned

    def on_op(self, name, single, outs):
        self.op_tape.append((name, single, tuple(
            (jax.ShapeDtypeStruct(tuple(o._buf.shape), o._buf.dtype),
             o.stop_gradient) for o in outs)))

    def on_read(self, t):
        k = id(t)
        if k in self.data_shadow:
            return self.data_shadow[k]
        if k in self.values:
            return self.values[k]
        buf = t._buf
        if isinstance(buf, jax.core.Tracer):
            return buf
        if t.persistable:
            raise MissedCapture(
                f"persistable tensor {t.name or id(t)!r} read during replay was "
                "not captured in the spy pass")
        return buf  # non-persistable external tensor: embed as constant

    def on_write(self, t, value):
        self.data_shadow[id(t)] = value

    def on_grad_read(self, t):
        k = id(t)
        if k in self.grad_shadow:
            v = self.grad_shadow[k]
            if v is None or isinstance(v, Tensor):
                return v
            return Tensor(v)
        if k in self.grad_lifted:
            return Tensor(self.grad_lifted[k])
        g = t._grad_buf
        if g is None:
            return None
        # a concrete pre-existing grad that the spy pass did not record would
        # be embedded as a stale trace-time constant — refuse and re-trace
        raise MissedCapture(
            f"pre-existing grad of {t.name or id(t)!r} read during replay was "
            "not captured in the spy pass")

    def on_grad_write(self, t, value):
        self.grad_shadow[id(t)] = value

    def resolve_tensor(self, t):
        """Current traced value of a Tensor inside this replay."""
        return self.on_read(t)


class _EchoContext:
    """Per-call python re-execution for break-stitched signatures: every op
    dispatch short-circuits to a shape-only placeholder (zero device compute),
    scalar guards replay their validated values, and float()/.numpy() breaks
    hand the python the TRUE values the compiled program just produced — so
    logging/metric side effects between breaks run once per call with correct
    data.  Reads of real tensors (args, params) return their pre-step buffers;
    writes are no-ops (the caller commits program outputs afterwards)."""

    mode = "echo"

    def __init__(self, entry, break_vals):
        self.op_tape = entry.op_tape
        self.op_idx = 0
        self.plan = entry.scalar_plan          # ordered kinds
        self.plan_idx = 0
        self._guards = iter(entry.guard_ints)  # pre-validated == actual
        self._breaks = iter(break_vals)

    def on_create(self, t):
        pass

    def on_read(self, t):
        return t._buf          # placeholder -> ShapeDtypeStruct, real -> array

    def on_write(self, t, value):
        pass

    def on_grad_read(self, t):
        return t._grad_buf

    def on_grad_write(self, t, value):
        pass

    def _next_kind(self, kind):
        i = self.plan_idx
        if i >= len(self.plan) or self.plan[i] != kind:
            raise EchoMismatch(
                f"scalar-conversion #{i} diverged from the trace "
                f"(expected {self.plan[i] if i < len(self.plan) else 'end'}, "
                f"got {kind})")
        self.plan_idx += 1

    def on_scalar(self, t, kind, caster):
        self._next_kind(kind)
        if kind == "bool":
            return bool(next(self._guards))
        if kind == "int":
            return int(next(self._guards))
        return float(next(self._breaks))

    def on_materialize(self, t):
        self._next_kind("numpy")
        return np.asarray(next(self._breaks))

    def on_op_echo(self, name, inputs):
        """Dispatch interception: validate against the trace's op tape and
        return placeholder outputs without executing anything."""
        i = self.op_idx
        if i >= len(self.op_tape) or self.op_tape[i][0] != name:
            raise EchoMismatch(
                f"op #{i} diverged from the trace (expected "
                f"{self.op_tape[i][0] if i < len(self.op_tape) else 'end'}, "
                f"got '{name}') — tensor ops appear to depend on a "
                "float()/.numpy() break value")
        self.op_idx += 1
        _, single, out_meta = self.op_tape[i]
        outs = [EchoPlaceholderTensor(sds, stop_gradient=sg)
                for sds, sg in out_meta]
        return outs[0] if single else tuple(outs)

    def finish(self):
        if self.op_idx != len(self.op_tape) or self.plan_idx != len(self.plan):
            raise EchoMismatch(
                "echo pass ended early: fewer ops/scalar reads than the "
                "trace recorded")


class _CacheEntry:
    __slots__ = ("compiled", "mut_list", "ro_list", "write_list", "grad_list",
                 "grad_in_list", "out_treedef", "out_mask",
                 "treedef", "guard_kinds", "guard_ints",
                 "scalar_plan", "break_kinds", "op_tape",
                 "scan_grad_slots", "scan_static")

    def __init__(self):
        self.compiled = None
        self.guard_kinds = ()
        self.guard_ints = ()     # specialized guard values, int-normalized
        self.scalar_plan = ()    # ordered kinds of ALL scalar events
        self.break_kinds = ()    # float/numpy break kinds, in order
        self.op_tape = ()        # (name, single, out_meta) from the trace


class _SigGroup:
    """All compiled variants for one argument signature. Multiple variants
    exist only when the fn has value guards (data-dependent branches): one
    per branch-combination actually taken."""
    __slots__ = ("variants", "eager_only", "last", "guard_warned")

    MAX_VARIANTS = 8

    def __init__(self):
        self.variants: list[_CacheEntry] = []
        self.eager_only = False
        self.last: _CacheEntry | None = None
        self.guard_warned = False


def _guard_ints(events):
    return tuple(int(v) for k, v in events if k in _GUARD_KINDS)


def _sig_key(leaves, treedef):
    parts = [str(treedef)]
    for l in leaves:
        if isinstance(l, Tensor):
            parts.append(
                f"T{tuple(l._buf.shape)}:{np.dtype(l._buf.dtype).name}:{l.stop_gradient}")
        else:
            try:
                parts.append(f"S{hash(l)}")
            except TypeError:
                parts.append(f"S{repr(l)}")
    return "|".join(parts)


class StaticFunction:
    # a MissedCapture during compile usually means the fn lazily CREATED state
    # on its first run (optimizer accumulators, RNG trackers) that becomes
    # external state from the second run on — re-spying then captures it.
    # Bounded so non-idempotent state creation can't re-spy forever.
    MAX_SPY_ATTEMPTS = 3

    def __init__(self, function, input_spec=None, build_strategy=None, backend=None,
                 full_graph=False, donate_state=True):
        self._fn = function
        self._cache: dict[str, _SigGroup] = {}
        self._spy_attempts: dict[str, int] = {}
        self._donate = donate_state
        self._obs_fn = getattr(function, "__name__", "?")
        try:
            functools.update_wrapper(self, function)
        except AttributeError:
            pass

    @property
    def function(self):
        return self._fn

    def __get__(self, instance, owner):
        if instance is None:
            return self
        return functools.partial(self.__call__, instance)

    def __call__(self, *args, **kwargs):
        if _state.trace_ctx is not None:
            return self._fn(*args, **kwargs)  # nested capture: inline
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
        key = _sig_key(leaves, treedef)
        group = self._cache.get(key)
        if group is None:
            return self._spy(key, leaves, treedef)
        if group.eager_only:
            _obs.JIT_EVENTS.inc(event="eager_call", fn=self._obs_fn)
            return self._fn(*args, **kwargs)
        entry = group.last if group.last is not None else group.variants[0]
        tried: set[int] = set()
        while True:
            tried.add(id(entry))
            try:
                result, actual = self._run(entry, leaves)
            except EchoMismatch as e:
                # the python's op sequence depends on a break value: the
                # compiled form cannot be trusted. Nothing was committed —
                # run this call eagerly (correct values, correct side
                # effects; pre-mismatch side effects may repeat once) and
                # pin the signature eager so this cannot loop silently.
                logger.warning(
                    "to_static: %s; falling back to eager and pinning this "
                    "signature eager-only. Hoist the break-dependent branch "
                    "out of the step (or use bool()/int(), which "
                    "re-specialize).", e)
                _obs.JIT_EVENTS.inc(event="echo_mismatch", fn=self._obs_fn)
                group.eager_only = True
                args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
                return self._fn(*args, **kwargs)
            except MissedCapture:
                logger.warning("to_static: capture miss; re-tracing")
                _obs.JIT_EVENTS.inc(event="retrace", fn=self._obs_fn)
                group.variants = [v for v in group.variants if v is not entry]
                group.last = None
                if not group.variants:
                    del self._cache[key]
                return self._spy(key, leaves, treedef)
            if actual is None or actual == entry.guard_ints:
                group.last = entry
                _obs.JIT_EVENTS.inc(event="cache_hit", fn=self._obs_fn)
                return result
            # guard divergence: this step took a different branch. The actual
            # guard values are trustworthy only up to (and including) the
            # first mismatch — after it the trace followed the wrong path.
            k = next(i for i, (a, b) in enumerate(zip(actual, entry.guard_ints))
                     if a != b)
            prefix = actual[:k + 1]
            nxt = next((v for v in group.variants
                        if id(v) not in tried
                        and v.guard_ints[:k + 1] == prefix), None)
            if nxt is None:
                logger.info("to_static: guard divergence at #%d; specializing "
                            "a new variant", k)
                _obs.JIT_EVENTS.inc(event="guard_divergence",
                                    fn=self._obs_fn)
                return self._spy(key, leaves, treedef)
            entry = nxt

    # ---- pass 1: eager spy ---------------------------------------------------
    def _spy(self, key, leaves, treedef):
        _obs.JIT_EVENTS.inc(event="capture", fn=self._obs_fn)
        group = self._cache.get(key)
        if group is None:
            group = self._cache[key] = _SigGroup()
        if len(group.variants) >= _SigGroup.MAX_VARIANTS:
            logger.warning(
                "to_static: %d guard-specialized variants for one signature; "
                "marking it eager-only", len(group.variants))
            group.eager_only = True
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            return self._fn(*args, **kwargs)
        ctx = _SpyContext()
        prev = _state.trace_ctx
        _state.trace_ctx = ctx
        try:
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            result = self._fn(*args, **kwargs)
        finally:
            _state.trace_ctx = prev
        entry = _CacheEntry()
        entry.treedef = treedef
        arg_ids = {id(l) for l in leaves if isinstance(l, Tensor)}
        write_ids = set(ctx.writes)
        reads = [t for k, t in ctx.reads.items()
                 if k not in arg_ids and hasattr(t._buf, "dtype")]
        entry.mut_list = [t for t in reads if id(t) in write_ids]
        entry.ro_list = [t for t in reads if id(t) not in write_ids]
        entry.write_list = [t for k, t in ctx.writes.items() if k not in arg_ids]
        entry.grad_list = list(ctx.grad_writes.values())
        entry.grad_in_list = [t for k, t in ctx.grad_reads.items()
                              if k not in arg_ids]
        entry.guard_kinds = tuple(k for k, _ in ctx.events
                                  if k in _GUARD_KINDS)
        entry.guard_ints = _guard_ints(ctx.events)
        entry.scalar_plan = tuple(k for k, _ in ctx.events)
        entry.break_kinds = tuple(k for k, _ in ctx.events
                                  if k in _BREAK_KINDS)
        group.variants.append(entry)
        group.last = entry
        try:
            self._compile(entry, leaves, ctx.events)
        except _BREAKS as e:
            logger.info("to_static: graph break (%s); signature stays eager",
                        type(e).__name__)
            group.eager_only = True
        except MissedCapture as e:
            attempts = self._spy_attempts.get(key, 0) + 1
            self._spy_attempts[key] = attempts
            group.variants.remove(entry)
            group.last = None
            if getattr(e, "permanent", False):
                logger.info("to_static: %s; signature stays eager", e)
                group.eager_only = True
            elif attempts < self.MAX_SPY_ATTEMPTS:
                # state created during this spy (lazy-init accumulators) is
                # external state next call — drop the entry so the next call
                # re-spies with that state pre-existing and fully captured
                logger.info("to_static: %s; re-spying on next call "
                            "(attempt %d)", e, attempts)
                if not group.variants:
                    del self._cache[key]
            else:
                logger.warning("to_static: %s after %d spy attempts; "
                               "signature stays eager", e, attempts)
                group.eager_only = True
        else:
            if entry.break_kinds:
                logger.info(
                    "to_static: signature compiled with %d stitched graph "
                    "break(s) (float()/.numpy() reads): the step stays one "
                    "fused program; a per-call echo pass replays the python "
                    "with true break values (plus one device->host sync).",
                    len(entry.break_kinds))
            if entry.guard_kinds and not group.guard_warned:
                # the guard check is a device->host sync per call: through a
                # remote dispatch path that is a full round trip (measured
                # 5-150 ms/call on the tunneled v5e — see BASELINE.md), and
                # a diverged step discards a fully executed compiled program.
                # Once per SIGNATURE: a later signature with its own guards
                # discloses its own cost
                group.guard_warned = True
                logger.warning(
                    "to_static: signature compiled with %d value guard(s) "
                    "(bool()/int() on tensors): every call pays a "
                    "device->host guard sync, which through a remote "
                    "dispatch path costs a full round trip. Hoist the "
                    "branch out of the step (or precompute it) for the "
                    "guard-free fast path.", len(entry.guard_kinds))
            if ctx.grad_writes:
                # train-step pattern (fn ran backward internally): replay-path
                # outputs are detached, so detach the spy outputs too — this
                # frees the spy tape immediately instead of holding the whole
                # step's activations until the caller drops the result
                for leaf in jax.tree_util.tree_leaves(result, is_leaf=_is_tensor):
                    if isinstance(leaf, Tensor):
                        leaf._grad_node = None
        return result

    # ---- build + jit the pure function --------------------------------------
    def _build_pure_fn(self, entry, leaves, events):
        """The captured step as a pure jax function
        (arg_arrays, mut_arrays, ro_arrays, grad_in_arrays) ->
        (out_vals, write_out, grad_out, guard_outs, break_outs). Shared by the
        plain jit path and the scan-over-steps path."""
        fn = self._fn
        treedef = entry.treedef
        tensor_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
        arg_meta = [(leaves[i].stop_gradient, leaves[i].name) for i in tensor_pos]
        events = list(events)

        def pure_fn(arg_arrays, mut_arrays, ro_arrays, grad_in_arrays):
            new_leaves = list(leaves)
            lifted: dict[int, object] = {}
            for j, i in enumerate(tensor_pos):
                sg, nm = arg_meta[j]
                t = Tensor(arg_arrays[j], stop_gradient=sg, name=nm)
                new_leaves[i] = t
                lifted[id(leaves[i])] = arg_arrays[j]  # closure reads of arg objs
            for t, arr in zip(entry.mut_list, mut_arrays):
                lifted[id(t)] = arr
            for t, arr in zip(entry.ro_list, ro_arrays):
                lifted[id(t)] = arr
            grad_lifted = {id(t): arr
                           for t, arr in zip(entry.grad_in_list, grad_in_arrays)}
            ctx = _ReplayContext(lifted, grad_lifted, plan=events)
            prev = _state.trace_ctx
            _state.trace_ctx = ctx
            try:
                args, kwargs = jax.tree_util.tree_unflatten(treedef, new_leaves)
                result = fn(*args, **kwargs)
                out_leaves, out_treedef = jax.tree_util.tree_flatten(
                    result, is_leaf=_is_tensor)
                out_mask = [isinstance(l, Tensor) for l in out_leaves]
                out_vals = [ctx.resolve_tensor(l) if isinstance(l, Tensor) else l
                            for l in out_leaves]
                write_out = [ctx.data_shadow.get(id(t), t._buf)
                             for t in entry.write_list]
                grad_out = []
                for t in entry.grad_list:
                    g = ctx.grad_shadow.get(id(t), t._grad_buf)
                    if isinstance(g, Tensor):
                        g = ctx.resolve_tensor(g)
                    grad_out.append(g)
            finally:
                _state.trace_ctx = prev
            if ctx.plan_idx != len(events):
                raise MissedCapture(
                    "replay consumed fewer scalar conversions than the spy "
                    "pass recorded")
            entry.out_treedef = out_treedef
            entry.out_mask = out_mask
            entry.op_tape = tuple(ctx.op_tape)
            return (out_vals, write_out, grad_out, ctx.guard_outs,
                    ctx.break_outs)

        return pure_fn

    def _compile(self, entry, leaves, events=()):
        events = list(events)
        pure_fn = self._build_pure_fn(entry, leaves, events)
        # guard-specialized variants re-run on divergence against the SAME
        # pre-step state, and break-stitched entries commit only after the
        # echo pass validates — neither may donate its inputs
        donate = (1,) if self._donate and entry.mut_list and not events else ()
        tensor_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
        arg_arrays = [leaves[i]._buf for i in tensor_pos]
        mut_arrays = [t._buf for t in entry.mut_list]
        ro_arrays = [t._buf for t in entry.ro_list]
        grad_in_arrays = self._grad_in_arrays(entry)
        # abstract trace now: surfaces graph breaks + fills out_treedef/
        # out_mask; at code_level>0 the SAME single trace yields the printed
        # jaxpr (make_jaxpr instead of a second eval_shape pass)
        from . import _code_level_value
        if _code_level_value() > 0:
            print(  # graftlint: disable=no-adhoc-telemetry (code_level dump)
                jax.make_jaxpr(pure_fn)(arg_arrays, mut_arrays, ro_arrays,
                                        grad_in_arrays))
        else:
            jax.eval_shape(pure_fn, arg_arrays, mut_arrays, ro_arrays,
                           grad_in_arrays)
        entry.compiled = jax.jit(pure_fn, donate_argnums=donate)

    @staticmethod
    def _grad_in_arrays(entry):
        arrays = []
        for t in entry.grad_in_list:
            g = t._grad_buf
            if g is None:
                raise MissedCapture(
                    f"grad of {t.name or id(t)!r} was live at capture time but is "
                    "now None")
            arrays.append(g._buf if isinstance(g, Tensor) else g)
        return arrays

    def _run(self, entry, leaves):
        """Run the compiled variant. Returns (result, actual_guard_values);
        actual is None for guard-free entries. State writes COMMIT only when
        the guards match (or there are none) AND, for break-stitched entries,
        after the echo pass confirms the python still follows the traced op
        sequence — a diverged run leaves all framework state untouched so the
        caller can re-run another variant or fall back to eager."""
        tensor_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
        arg_arrays = [leaves[i]._buf for i in tensor_pos]
        mut_arrays = [t._buf for t in entry.mut_list]
        ro_arrays = [t._buf for t in entry.ro_list]
        out_vals, write_out, grad_out, guard_out, break_out = entry.compiled(
            arg_arrays, mut_arrays, ro_arrays, self._grad_in_arrays(entry))
        actual = None
        if entry.guard_kinds:
            actual = tuple(int(v) for v in jax.device_get(guard_out))
            if actual != entry.guard_ints:
                return None, actual
        if entry.break_kinds:
            self._echo(entry, leaves, jax.device_get(break_out))
        for t, arr in zip(entry.write_list, write_out):
            t._buf = arr
        for t, g in zip(entry.grad_list, grad_out):
            t._grad_buf = Tensor(g) if g is not None and not isinstance(g, Tensor) else g
        out_leaves = [Tensor(v) if m else v
                      for v, m in zip(out_vals, entry.out_mask)]
        return jax.tree_util.tree_unflatten(entry.out_treedef, out_leaves), actual

    def _echo(self, entry, leaves, break_vals):
        """Re-run the python with op dispatches short-circuited so side
        effects between breaks observe the true per-call values.  Any
        divergence or failure raises EchoMismatch BEFORE state commits."""
        ctx = _EchoContext(entry, break_vals)
        prev = _state.trace_ctx
        _state.trace_ctx = ctx
        try:
            args, kwargs = jax.tree_util.tree_unflatten(entry.treedef, leaves)
            self._fn(*args, **kwargs)
            ctx.finish()
        except EchoMismatch:
            raise
        except Exception as e:
            raise EchoMismatch(
                f"echo pass failed ({type(e).__name__}: {e})") from e
        finally:
            _state.trace_ctx = prev


class ScanStaticFunction(StaticFunction):
    """K steps per dispatched call: the fn is captured once at per-step shapes
    and compiled as ONE ``lax.scan`` over the leading axis of every tensor
    argument.

    TPU-native rationale: through a remote dispatch path (e.g. a tunneled
    PJRT client) every jitted call pays a full round trip; scanning K steps
    inside one compiled program amortizes that to RTT/K with an HLO whose
    size is independent of K (the unrolled alternative grows linearly with K
    and recompiles whenever K changes). This is the idiomatic JAX
    epoch-as-scan training loop surfaced as a framework primitive.

    Semantics: each tensor argument is stacked on axis 0 ([K, ...]); the fn
    runs K times in order; outputs come back stacked on axis 0. External
    state (params, optimizer moments, RNG keys) threads through the scan
    carry, so K optimizer updates really happen. The FIRST call with a new
    signature runs all K slices eagerly (the capture pass) and is slow;
    subsequent calls are a single fused dispatch.

    Restrictions (checked at capture; violations fall back to an eager
    per-slice loop): no value guards (bool()/int() data-dependent branches)
    and no pre-existing grads read — the step must be self-contained (grads
    produced and consumed/cleared within one call). Grads left set at step
    end hold the LAST slice's values, matching a per-slice eager loop only
    when each step overwrites rather than accumulates across steps.

    ``unroll``: lax.scan unroll factor (HLO grows proportionally; can
    recover cross-step fusion / shave while-loop overhead).
    """

    def __init__(self, function, input_spec=None, build_strategy=None,
                 backend=None, full_graph=False, donate_state=True, unroll=1):
        super().__init__(function, input_spec, build_strategy, backend,
                         full_graph, donate_state)
        self._unroll = max(1, int(unroll))

    def __call__(self, *args, **kwargs):
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs),
                                                     is_leaf=_is_tensor)
        k = self._k_of(leaves)
        if _state.trace_ctx is not None:   # nested capture: inline eagerly
            return self._eager_scan(leaves, treedef, k)
        key = _sig_key(leaves, treedef)
        group = self._cache.get(key)
        if group is None:
            return self._spy_scan(key, leaves, treedef, k)
        if group.eager_only:
            _obs.JIT_EVENTS.inc(event="eager_call", fn=self._obs_fn)
            return self._eager_scan(leaves, treedef, k)
        entry = group.variants[0]
        try:
            result, _ = self._run(entry, leaves)
            _obs.JIT_EVENTS.inc(event="cache_hit", fn=self._obs_fn)
            return result
        except MissedCapture:
            logger.warning("to_static[scan]: capture miss; re-tracing")
            _obs.JIT_EVENTS.inc(event="retrace", fn=self._obs_fn)
            group.variants = [v for v in group.variants if v is not entry]
            group.last = None
            if not group.variants:
                del self._cache[key]
            return self._spy_scan(key, leaves, treedef, k)

    @staticmethod
    def _k_of(leaves):
        ks = {l._buf.shape[0] for l in leaves
              if isinstance(l, Tensor) and getattr(l._buf, "ndim", 0) > 0}
        scalars = [l for l in leaves
                   if isinstance(l, Tensor) and getattr(l._buf, "ndim", 0) == 0]
        if scalars or len(ks) != 1 or 0 in ks:
            raise ValueError(
                "scan_steps: every tensor argument must be stacked on one "
                f"shared non-empty leading (step) dim; got leading dims "
                f"{sorted(ks)}"
                + (" plus scalar tensor args" if scalars else ""))
        return ks.pop()

    @staticmethod
    def _slice(leaves, i):
        # read through the dispatch unwrap so a nested capture (outer spy or
        # replay) records/lifts the argument read instead of baking in the
        # concrete capture-time buffer
        from ..core.dispatch import unwrap
        return [Tensor(unwrap(l)[i], stop_gradient=l.stop_gradient,
                       name=l.name)
                if isinstance(l, Tensor) else l for l in leaves]

    def _eager_scan(self, leaves, treedef, k):
        results = []
        for i in range(k):
            args, kwargs = jax.tree_util.tree_unflatten(
                treedef, self._slice(leaves, i))
            results.append(self._fn(*args, **kwargs))
        return self._stack_results(results)

    @staticmethod
    def _stack_results(results):
        import jax.numpy as jnp
        flat0, rtree = jax.tree_util.tree_flatten(results[0],
                                                  is_leaf=_is_tensor)
        cols = [jax.tree_util.tree_flatten(r, is_leaf=_is_tensor)[0]
                for r in results]
        stacked = []
        for j, leaf in enumerate(flat0):
            if isinstance(leaf, Tensor):
                stacked.append(
                    Tensor(jnp.stack([c[j]._buf for c in cols])))
            elif hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
                # raw array leaf: stack to match the compiled path, which
                # rides it through the scan ys as [K, ...]
                stacked.append(jnp.stack([c[j] for c in cols]))
            else:
                stacked.append(cols[-1][j])
        return jax.tree_util.tree_unflatten(rtree, stacked)

    def _spy_scan(self, key, leaves, treedef, k):
        from ..core import flags
        self._pending_k = k
        # slice 0 runs under the spy (records reads/writes, compiles the
        # scan); the remaining slices run eagerly so the capturing call
        # still performs all K steps with exact per-slice semantics.
        # FLAGS_eager_recompute_grad keeps those warmup slices on the
        # deferred-vjp memory profile (the spy's own mode) — plain eager
        # holds per-op jax.vjp residuals and OOMs at capture on geometries
        # the compiled scan itself fits comfortably
        results = [self._spy(key, self._slice(leaves, 0), treedef)]
        prev = flags.flag("eager_recompute_grad")
        flags.set_flags({"FLAGS_eager_recompute_grad": True})
        try:
            for i in range(1, k):
                args, kwargs = jax.tree_util.tree_unflatten(
                    treedef, self._slice(leaves, i))
                results.append(self._fn(*args, **kwargs))
        finally:
            flags.set_flags({"FLAGS_eager_recompute_grad": prev})
        return self._stack_results(results)

    def _compile(self, entry, leaves, events=()):
        import jax.numpy as jnp
        if events:
            raise MissedCapture(
                "scan_steps does not support value-guarded (bool()/int()) "
                "branches or stitched breaks (float()/.numpy()) inside the "
                "step — hoist host reads out of the scanned region",
                permanent=True)
        if entry.grad_in_list:
            raise MissedCapture(
                "scan_steps requires a self-contained step (no pre-existing "
                "grads read; clear grads inside the step or use to_static)",
                permanent=True)
        k = self._pending_k
        pure_fn = self._build_pure_fn(entry, leaves, [])
        tensor_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]

        def _sds(buf):
            return jax.ShapeDtypeStruct(tuple(buf.shape),
                                        np.dtype(buf.dtype))

        slice_shapes = [_sds(leaves[i]._buf) for i in tensor_pos]
        mut_shapes = [_sds(t._buf) for t in entry.mut_list]
        ro_shapes = [_sds(t._buf) for t in entry.ro_list]
        # one abstract pass over the single step: surfaces graph breaks,
        # fills out_treedef/out_mask, and yields the grad-write structure so
        # non-None grads can ride the scan carry
        try:
            shapes = jax.eval_shape(pure_fn, slice_shapes, mut_shapes,
                                    ro_shapes, [])
        except _BREAKS:
            raise
        except MissedCapture:
            raise
        except Exception as e:
            raise MissedCapture(
                f"step trace failed ({type(e).__name__}: {e})") from e
        _, write_shapes, grad_shapes, _, _ = shapes
        entry.scan_grad_slots = tuple(
            i for i, g in enumerate(grad_shapes) if g is not None)
        grad_slots = entry.scan_grad_slots
        write_pos = {id(t): i for i, t in enumerate(entry.write_list)}
        mut_idx = [write_pos[id(t)] for t in entry.mut_list]
        for t, s in zip(entry.write_list, write_shapes):
            cur = t._buf
            if (tuple(cur.shape) != tuple(s.shape)
                    or np.dtype(cur.dtype) != np.dtype(s.dtype)):
                raise MissedCapture(
                    f"state tensor {t.name or id(t)!r} changes shape/dtype "
                    "across steps; scan_steps needs a shape-stable carry")
        # non-Tensor output leaves: trace-time constants (python scalars)
        # return as-is on every path; tracer-valued non-Tensor leaves (raw
        # arrays) ride the scan ys. scan_static[j] holds the constants.
        scan_static: dict[int, object] = {}

        def scan_fn(stacked_args, state_arrays, ro_arrays):
            def body(carry, xs):
                state, grads = carry
                mut = [state[i] for i in mut_idx]
                out_vals, write_out, grad_out, _, _ = pure_fn(
                    list(xs), mut, list(ro_arrays), [])
                ys = []
                for j, (v, m) in enumerate(zip(out_vals, entry.out_mask)):
                    # array-valued leaves (traced OR constant) ride the scan
                    # ys as [K, ...] — matching _stack_results on the eager
                    # capture call; only python scalars stay static
                    if m or (hasattr(v, "dtype") and hasattr(v, "shape")):
                        ys.append(jnp.asarray(v))
                    else:
                        scan_static[j] = v
                new_grads = [grad_out[i] for i in grad_slots]
                return (list(write_out), new_grads), ys

            init_grads = [jnp.zeros(grad_shapes[i].shape,
                                    grad_shapes[i].dtype)
                          for i in grad_slots]
            (fin_state, fin_grads), ys = jax.lax.scan(
                body, (list(state_arrays), init_grads), tuple(stacked_args),
                unroll=self._unroll)
            return ys, fin_state, fin_grads

        stacked_shapes = [jax.ShapeDtypeStruct(
            (k,) + tuple(leaves[i]._buf.shape),
            np.dtype(leaves[i]._buf.dtype)) for i in tensor_pos]
        state_shapes = [_sds(t._buf) for t in entry.write_list]
        try:
            from . import _code_level_value
            if _code_level_value() > 0:
                print(  # graftlint: disable=no-adhoc-telemetry (code_level dump)
                    jax.make_jaxpr(scan_fn)(stacked_shapes, state_shapes,
                                            ro_shapes))
            else:
                jax.eval_shape(scan_fn, stacked_shapes, state_shapes,
                               ro_shapes)
        except _BREAKS:
            raise
        except MissedCapture:
            raise
        except Exception as e:  # carry-structure mismatches etc.
            raise MissedCapture(
                f"scan trace failed ({type(e).__name__}: {e})") from e
        entry.scan_static = dict(scan_static)
        donate = (1,) if self._donate and entry.write_list else ()
        entry.compiled = jax.jit(scan_fn, donate_argnums=donate)

    def _run(self, entry, leaves):
        tensor_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
        stacked = [leaves[i]._buf for i in tensor_pos]
        state = [t._buf for t in entry.write_list]
        ro = [t._buf for t in entry.ro_list]
        ys, fin_state, fin_grads = entry.compiled(stacked, state, ro)
        for t, arr in zip(entry.write_list, fin_state):
            t._buf = arr
        gmap = dict(zip(entry.scan_grad_slots, fin_grads))
        for i, t in enumerate(entry.grad_list):
            g = gmap.get(i)
            t._grad_buf = Tensor(g) if g is not None else None
        out_leaves, ys_it = [], iter(ys)
        for j, m in enumerate(entry.out_mask):
            if j in entry.scan_static:
                out_leaves.append(entry.scan_static[j])
            else:
                v = next(ys_it)
                out_leaves.append(Tensor(v) if m else v)
        return jax.tree_util.tree_unflatten(entry.out_treedef, out_leaves), None


def scan_steps(function=None, donate_state=True, unroll=1):
    """Compile ``function`` to run K steps per dispatched call via one fused
    ``lax.scan`` — call the result with every tensor argument stacked on a
    leading [K, ...] axis; outputs come back stacked the same way and K
    optimizer updates really happen. See :class:`ScanStaticFunction` for
    semantics and restrictions. TPU-native answer to per-dispatch round-trip
    latency (no reference analog: Paddle's executor amortizes per-op launch
    with C++ scheduling, which a remote-dispatch TPU client cannot)."""
    def wrap(f):
        if isinstance(f, ScanStaticFunction):
            return f
        if isinstance(f, StaticFunction):
            f = f.function
        return ScanStaticFunction(f, donate_state=donate_state,
                                  unroll=unroll)
    if function is not None:
        return wrap(function)
    return wrap


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              full_graph=False, **kwargs):
    """paddle.jit.to_static decorator/wrapper."""
    def wrap(f):
        if isinstance(f, StaticFunction):
            return f
        from ..nn.layer.layers import Layer
        if isinstance(f, Layer):
            layer = f
            sf = StaticFunction(layer.forward, input_spec)
            layer.forward = sf
            layer._static_function = sf
            return layer
        return StaticFunction(f, input_spec)
    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(fn):
    fn._not_to_static = True
    return fn
