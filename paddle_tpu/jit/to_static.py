"""Program capture — paddle.jit.to_static (reference: python/paddle/jit/api.py:197
+ the SOT bytecode frontend python/paddle/jit/sot/).

TPU-native redesign: instead of CPython bytecode simulation, capture exploits the
framework's trace-transparent eager core (every op goes through one dispatch
chokepoint; Tensor state reads/writes go through properties):

  call 1 (SPY)    — runs eagerly at full fidelity while recording which external
                    tensors the function READS (params, buffers, optimizer
                    moments, RNG key) and which it WRITES (param update, moment
                    update, key split, .grad assignment).
  call 2+ (REPLAY)— a pure jax function (args, mutated-state, readonly-state) ->
                    (outputs, new-state), jit-compiled with donation of the
                    mutated state buffers; re-runs the SAME python under tracers
                    with shadowed writes. One fused XLA program = fwd + bwd +
                    optimizer step.

Guards: arg treedef + shapes/dtypes + static-arg values (the SOT guard analog) —
a new signature re-traces. Graph breaks: TracerBoolConversionError /
ConcretizationTypeError (data-dependent python control flow) or capture misses
mark the signature eager-only — the SOT graph-break fallback analog. Shapes are
static per signature; variable seq-len is handled by bucketing above (SURVEY §7).
"""
from __future__ import annotations

import functools
import logging

import numpy as np
import jax

from ..core.tensor import Tensor
from ..core.dispatch import _state

logger = logging.getLogger("paddle_tpu.jit")

_BREAKS = (jax.errors.TracerBoolConversionError,
           jax.errors.ConcretizationTypeError,
           jax.errors.TracerArrayConversionError,
           jax.errors.TracerIntegerConversionError)


class MissedCapture(Exception):
    pass


def _is_tensor(x):
    return isinstance(x, Tensor)


class _SpyContext:
    """Eager pass-through that records external reads + writes."""

    mode = "spy"

    def __init__(self):
        self.reads: dict[int, Tensor] = {}
        self.writes: dict[int, Tensor] = {}
        self.grad_reads: dict[int, Tensor] = {}
        self.grad_writes: dict[int, Tensor] = {}
        self.created: set[int] = set()

    def on_create(self, t):
        self.created.add(id(t))

    def on_read(self, t):
        if id(t) not in self.created:
            self.reads.setdefault(id(t), t)
        return t._buf

    def on_write(self, t, value):
        if id(t) not in self.created:
            self.writes.setdefault(id(t), t)
        t._buf = value

    def on_grad_read(self, t):
        # a pre-existing grad read before any write this step (gradient
        # accumulation with clear_grad outside the captured fn) is external
        # state: record it so replay lifts it to a program input instead of
        # baking the spy pass's concrete grad in as a trace constant
        if (t._grad_buf is not None and id(t) not in self.created
                and id(t) not in self.grad_writes):
            self.grad_reads.setdefault(id(t), t)
        return t._grad_buf

    def on_grad_write(self, t, value):
        if id(t) not in self.created:
            self.grad_writes.setdefault(id(t), t)
        t._grad_buf = value


class _ReplayContext:
    """Pure traced re-execution: reads hit lifted tracers, writes go to shadows."""

    mode = "replay"

    def __init__(self, lifted: dict[int, object], grad_lifted=None):
        self.values = lifted                  # id(Tensor) -> traced array
        self.grad_lifted = grad_lifted or {}  # id(Tensor) -> traced grad array
        self.data_shadow: dict[int, object] = {}
        self.grad_shadow: dict[int, object] = {}

    def on_create(self, t):
        pass

    def on_read(self, t):
        k = id(t)
        if k in self.data_shadow:
            return self.data_shadow[k]
        if k in self.values:
            return self.values[k]
        buf = t._buf
        if isinstance(buf, jax.core.Tracer):
            return buf
        if t.persistable:
            raise MissedCapture(
                f"persistable tensor {t.name or id(t)!r} read during replay was "
                "not captured in the spy pass")
        return buf  # non-persistable external tensor: embed as constant

    def on_write(self, t, value):
        self.data_shadow[id(t)] = value

    def on_grad_read(self, t):
        k = id(t)
        if k in self.grad_shadow:
            v = self.grad_shadow[k]
            if v is None or isinstance(v, Tensor):
                return v
            return Tensor(v)
        if k in self.grad_lifted:
            return Tensor(self.grad_lifted[k])
        g = t._grad_buf
        if g is None:
            return None
        # a concrete pre-existing grad that the spy pass did not record would
        # be embedded as a stale trace-time constant — refuse and re-trace
        raise MissedCapture(
            f"pre-existing grad of {t.name or id(t)!r} read during replay was "
            "not captured in the spy pass")

    def on_grad_write(self, t, value):
        self.grad_shadow[id(t)] = value

    def resolve_tensor(self, t):
        """Current traced value of a Tensor inside this replay."""
        return self.on_read(t)


class _CacheEntry:
    __slots__ = ("compiled", "mut_list", "ro_list", "write_list", "grad_list",
                 "grad_in_list", "out_treedef", "out_mask", "eager_only", "treedef")

    def __init__(self):
        self.compiled = None
        self.eager_only = False


def _sig_key(leaves, treedef):
    parts = [str(treedef)]
    for l in leaves:
        if isinstance(l, Tensor):
            parts.append(
                f"T{tuple(l._buf.shape)}:{np.dtype(l._buf.dtype).name}:{l.stop_gradient}")
        else:
            try:
                parts.append(f"S{hash(l)}")
            except TypeError:
                parts.append(f"S{repr(l)}")
    return "|".join(parts)


class StaticFunction:
    # a MissedCapture during compile usually means the fn lazily CREATED state
    # on its first run (optimizer accumulators, RNG trackers) that becomes
    # external state from the second run on — re-spying then captures it.
    # Bounded so non-idempotent state creation can't re-spy forever.
    MAX_SPY_ATTEMPTS = 3

    def __init__(self, function, input_spec=None, build_strategy=None, backend=None,
                 full_graph=False, donate_state=True):
        self._fn = function
        self._cache: dict[str, _CacheEntry] = {}
        self._spy_attempts: dict[str, int] = {}
        self._donate = donate_state
        try:
            functools.update_wrapper(self, function)
        except AttributeError:
            pass

    @property
    def function(self):
        return self._fn

    def __get__(self, instance, owner):
        if instance is None:
            return self
        return functools.partial(self.__call__, instance)

    def __call__(self, *args, **kwargs):
        if _state.trace_ctx is not None:
            return self._fn(*args, **kwargs)  # nested capture: inline
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
        key = _sig_key(leaves, treedef)
        entry = self._cache.get(key)
        if entry is None:
            return self._spy(key, leaves, treedef)
        if entry.eager_only:
            return self._fn(*args, **kwargs)
        try:
            return self._run(entry, leaves)
        except MissedCapture:
            logger.warning("to_static: capture miss; re-tracing")
            del self._cache[key]
            return self._spy(key, leaves, treedef)

    # ---- pass 1: eager spy ---------------------------------------------------
    def _spy(self, key, leaves, treedef):
        ctx = _SpyContext()
        prev = _state.trace_ctx
        _state.trace_ctx = ctx
        try:
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            result = self._fn(*args, **kwargs)
        finally:
            _state.trace_ctx = prev
        entry = _CacheEntry()
        entry.treedef = treedef
        arg_ids = {id(l) for l in leaves if isinstance(l, Tensor)}
        write_ids = set(ctx.writes)
        reads = [t for k, t in ctx.reads.items()
                 if k not in arg_ids and hasattr(t._buf, "dtype")]
        entry.mut_list = [t for t in reads if id(t) in write_ids]
        entry.ro_list = [t for t in reads if id(t) not in write_ids]
        entry.write_list = [t for k, t in ctx.writes.items() if k not in arg_ids]
        entry.grad_list = list(ctx.grad_writes.values())
        entry.grad_in_list = [t for k, t in ctx.grad_reads.items()
                              if k not in arg_ids]
        self._cache[key] = entry
        try:
            self._compile(entry, leaves)
        except _BREAKS as e:
            logger.info("to_static: graph break (%s); signature stays eager",
                        type(e).__name__)
            entry.eager_only = True
        except MissedCapture as e:
            attempts = self._spy_attempts.get(key, 0) + 1
            self._spy_attempts[key] = attempts
            if attempts < self.MAX_SPY_ATTEMPTS:
                # state created during this spy (lazy-init accumulators) is
                # external state next call — drop the entry so the next call
                # re-spies with that state pre-existing and fully captured
                logger.info("to_static: %s; re-spying on next call "
                            "(attempt %d)", e, attempts)
                del self._cache[key]
            else:
                logger.warning("to_static: %s after %d spy attempts; "
                               "signature stays eager", e, attempts)
                entry.eager_only = True
        return result

    # ---- build + jit the pure function --------------------------------------
    def _compile(self, entry, leaves):
        fn = self._fn
        treedef = entry.treedef
        tensor_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
        arg_meta = [(leaves[i].stop_gradient, leaves[i].name) for i in tensor_pos]

        def pure_fn(arg_arrays, mut_arrays, ro_arrays, grad_in_arrays):
            new_leaves = list(leaves)
            lifted: dict[int, object] = {}
            for j, i in enumerate(tensor_pos):
                sg, nm = arg_meta[j]
                t = Tensor(arg_arrays[j], stop_gradient=sg, name=nm)
                new_leaves[i] = t
                lifted[id(leaves[i])] = arg_arrays[j]  # closure reads of arg objs
            for t, arr in zip(entry.mut_list, mut_arrays):
                lifted[id(t)] = arr
            for t, arr in zip(entry.ro_list, ro_arrays):
                lifted[id(t)] = arr
            grad_lifted = {id(t): arr
                           for t, arr in zip(entry.grad_in_list, grad_in_arrays)}
            ctx = _ReplayContext(lifted, grad_lifted)
            prev = _state.trace_ctx
            _state.trace_ctx = ctx
            try:
                args, kwargs = jax.tree_util.tree_unflatten(treedef, new_leaves)
                result = fn(*args, **kwargs)
                out_leaves, out_treedef = jax.tree_util.tree_flatten(
                    result, is_leaf=_is_tensor)
                out_mask = [isinstance(l, Tensor) for l in out_leaves]
                out_vals = [ctx.resolve_tensor(l) if isinstance(l, Tensor) else l
                            for l in out_leaves]
                write_out = [ctx.data_shadow.get(id(t), t._buf)
                             for t in entry.write_list]
                grad_out = []
                for t in entry.grad_list:
                    g = ctx.grad_shadow.get(id(t), t._grad_buf)
                    if isinstance(g, Tensor):
                        g = ctx.resolve_tensor(g)
                    grad_out.append(g)
            finally:
                _state.trace_ctx = prev
            entry.out_treedef = out_treedef
            entry.out_mask = out_mask
            return out_vals, write_out, grad_out

        donate = (1,) if self._donate and entry.mut_list else ()
        arg_arrays = [leaves[i]._buf for i in tensor_pos]
        mut_arrays = [t._buf for t in entry.mut_list]
        ro_arrays = [t._buf for t in entry.ro_list]
        grad_in_arrays = self._grad_in_arrays(entry)
        # abstract trace now: surfaces graph breaks + fills out_treedef/
        # out_mask; at code_level>0 the SAME single trace yields the printed
        # jaxpr (make_jaxpr instead of a second eval_shape pass)
        from . import _code_level_value
        if _code_level_value() > 0:
            print(jax.make_jaxpr(pure_fn)(arg_arrays, mut_arrays, ro_arrays,
                                          grad_in_arrays))
        else:
            jax.eval_shape(pure_fn, arg_arrays, mut_arrays, ro_arrays,
                           grad_in_arrays)
        entry.compiled = jax.jit(pure_fn, donate_argnums=donate)

    @staticmethod
    def _grad_in_arrays(entry):
        arrays = []
        for t in entry.grad_in_list:
            g = t._grad_buf
            if g is None:
                raise MissedCapture(
                    f"grad of {t.name or id(t)!r} was live at capture time but is "
                    "now None")
            arrays.append(g._buf if isinstance(g, Tensor) else g)
        return arrays

    def _run(self, entry, leaves):
        tensor_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
        arg_arrays = [leaves[i]._buf for i in tensor_pos]
        mut_arrays = [t._buf for t in entry.mut_list]
        ro_arrays = [t._buf for t in entry.ro_list]
        out_vals, write_out, grad_out = entry.compiled(
            arg_arrays, mut_arrays, ro_arrays, self._grad_in_arrays(entry))
        for t, arr in zip(entry.write_list, write_out):
            t._buf = arr
        for t, g in zip(entry.grad_list, grad_out):
            t._grad_buf = Tensor(g) if g is not None and not isinstance(g, Tensor) else g
        out_leaves = [Tensor(v) if m else v
                      for v, m in zip(out_vals, entry.out_mask)]
        return jax.tree_util.tree_unflatten(entry.out_treedef, out_leaves)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              full_graph=False, **kwargs):
    """paddle.jit.to_static decorator/wrapper."""
    def wrap(f):
        if isinstance(f, StaticFunction):
            return f
        from ..nn.layer.layers import Layer
        if isinstance(f, Layer):
            layer = f
            sf = StaticFunction(layer.forward, input_spec)
            layer.forward = sf
            layer._static_function = sf
            return layer
        return StaticFunction(f, input_spec)
    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(fn):
    fn._not_to_static = True
    return fn
