# graftlint: disable-file=no-adhoc-telemetry  (CLI front-end: stdout is the UI)
"""graftlint CLI — ``python -m paddle_tpu.analysis`` / the ``graftlint``
console script.

Exit codes: 0 clean, 1 findings at or above ``--fail-on`` (default: error),
2 usage/internal error.

JSON report schema (``--format json``)::

    {
      "graftlint": 1,                 # schema version
      "passes": ["jit-cache-hygiene", ...],
      "files": 182,
      "suppressed": 3,                # pragma-suppressed findings
      "baselined": 2,                 # findings absorbed by --baseline
      "cache_hits": 170,
      "findings": [
        {"pass": "trace-safety", "code": "TS101",
         "path": "paddle_tpu/x.py", "line": 42,
         "message": "...", "hint": "...", "severity": "error"}
      ]
    }

``--format sarif`` emits SARIF 2.1.0 for CI annotation (GitHub code
scanning et al.); ``--baseline FILE`` suppresses previously accepted
findings and ``--write-baseline FILE`` records the current ones.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _rule_lines():
    """One ``name: CODE, CODE, ...`` line per registered pass (epilog,
    --version).  Imports the built-in passes as a side effect."""
    from . import passes as _passes  # noqa: F401 — register built-ins
    from .framework import PASSES
    out = []
    for name in sorted(PASSES):
        codes = ", ".join(PASSES[name].codes) or "(no stable rule IDs)"
        out.append(f"  {name:24s} {codes}")
    return out


def _parser():
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="trace-safety, registry-parity, sharding, dtype and "
                    "lock-discipline static analysis for the paddle_tpu "
                    "tree",
        formatter_class=argparse.RawDescriptionHelpFormatter,
        epilog="rule IDs by pass:\n" + "\n".join(_rule_lines()))
    p.add_argument("paths", nargs="*", default=["."],
                   help="files or directories to lint (default: .)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--select", metavar="PASS[,PASS]",
                   help="run only these passes")
    p.add_argument("--disable", metavar="PASS[,PASS]",
                   help="skip these passes")
    p.add_argument("--fail-on", choices=("error", "warning"), default="error",
                   help="lowest severity that fails the run (default: error; "
                        "'warning' makes any finding fatal)")
    p.add_argument("--baseline", metavar="FILE",
                   help="skip findings recorded in this baseline file")
    p.add_argument("--write-baseline", metavar="FILE",
                   help="write the surviving findings to FILE as the new "
                        "baseline and exit 0")
    p.add_argument("--no-cache", action="store_true",
                   help="ignore and don't write the per-file result cache")
    p.add_argument("--cache", metavar="FILE",
                   help="cache file (default: $GRAFTLINT_CACHE or "
                        "~/.cache/graftlint/cache.json)")
    p.add_argument("--list-passes", action="store_true",
                   help="list registered passes with their rule IDs")
    p.add_argument("--explain", metavar="CODE",
                   help="print a rule's doc, severity and its minimal "
                        "bad/clean fixture example, then exit")
    p.add_argument("--version", action="store_true",
                   help="print pass versions and rule IDs, then exit")
    return p


def _split(s):
    return [x.strip() for x in s.split(",") if x.strip()] if s else None


def _fixture_pair(code):
    """(bad_path, clean_path) for ``code``'s fixture pair under
    ``tests/graftlint_fixtures`` when the repo checkout is present."""
    import glob
    import os
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    fixtures = os.path.join(repo, "tests", "graftlint_fixtures")
    bad = sorted(glob.glob(os.path.join(fixtures, f"*{code}_bad.py")))
    clean = sorted(glob.glob(os.path.join(fixtures, f"*{code}_clean.py")))
    return (bad[0] if bad else None), (clean[0] if clean else None)


def _explain(code) -> int:
    from . import passes as _passes  # noqa: F401 — register built-ins
    from .framework import PASSES
    code = code.upper()
    for name in sorted(PASSES):
        p = PASSES[name]
        if code not in p.codes:
            continue
        print(f"{code} [{name} v{p.version}]")
        print(f"severity: {p.rule_severities.get(code, 'error')}")
        doc = p.rule_docs.get(code) or p.description
        print(f"\n{doc}\n")
        bad, clean = _fixture_pair(code.lower())
        for label, path in (("bad", bad), ("clean", clean)):
            if path is None:
                continue
            with open(path, encoding="utf-8") as f:
                body = f.read().rstrip()
            print(f"--- {label} example ({path.rsplit('/', 1)[-1]}) ---")
            print(body)
            print()
        if bad is None and clean is None:
            print("(no fixture pair found — repo checkout required for "
                  "examples)")
        return 0
    print(f"graftlint: unknown rule code {code!r}", file=sys.stderr)
    return 2


def main(argv=None) -> int:
    try:
        return _main(argv)
    except BrokenPipeError:
        # reader (head, less …) went away mid-report; the EPIPE is theirs
        # to cause, not ours to traceback over.  Re-point stdout at
        # /dev/null so the interpreter's exit-time flush doesn't raise too.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


def _main(argv=None) -> int:
    args = _parser().parse_args(argv)
    from . import passes as _passes  # noqa: F401 — register built-ins
    from .framework import PASSES, run
    if args.version:
        from .cache import _SCHEMA
        print(f"graftlint (cache schema v{_SCHEMA})")
        for line in _rule_lines():
            print(line)
        return 0
    if args.explain:
        return _explain(args.explain)
    if args.list_passes:
        for name in sorted(PASSES):
            p = PASSES[name]
            scope = ("project" if p.project_scope
                     else "summary" if p.summary_scope else "file")
            codes = " ".join(p.codes)
            print(f"{name:24s} v{p.version} [{scope}]  {p.description}")
            if codes:
                print(f"{'':24s} rules: {codes}")
        return 0
    cache = None
    if not args.no_cache:
        from .cache import FileCache
        cache = FileCache(args.cache)
    baseline = None
    if args.baseline:
        from .baseline import Baseline
        baseline = Baseline.load(args.baseline)
    try:
        result = run(args.paths, select=_split(args.select),
                     disable=_split(args.disable), cache=cache,
                     baseline=baseline)
    except KeyError as e:
        print(f"graftlint: {e.args[0]}", file=sys.stderr)
        return 2
    if args.write_baseline:
        from .baseline import Baseline
        n = Baseline.write(args.write_baseline, result.findings)
        print(f"graftlint: wrote {n} finding(s) to {args.write_baseline}")
        return 0
    if args.format == "json":
        from .report import to_json
        print(json.dumps(to_json(result), indent=2))
    elif args.format == "sarif":
        from .report import to_sarif
        print(json.dumps(to_sarif(result), indent=2))
    else:
        for f in result.findings:
            print(f.render())
        n_err = len(result.errors())
        n_warn = len(result.findings) - n_err
        tail = (f"{n_err} error(s), {n_warn} warning(s) in {result.files} "
                f"file(s); {result.suppressed} suppressed by pragma"
                + (f", {result.baselined} baselined" if result.baselined
                   else ""))
        failing = result.findings if args.fail_on == "warning" \
            else result.errors()
        print(("FAILED: " if failing else "OK: ") + tail)
    failing = result.findings if args.fail_on == "warning" \
        else result.errors()
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
