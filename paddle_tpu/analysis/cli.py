# graftlint: disable-file=no-adhoc-telemetry  (CLI front-end: stdout is the UI)
"""graftlint CLI — ``python -m paddle_tpu.analysis`` / the ``graftlint``
console script.

Exit codes: 0 clean, 1 findings, 2 usage/internal error.

JSON report schema (``--format json``)::

    {
      "graftlint": 1,                 # schema version
      "passes": ["jit-cache-hygiene", ...],
      "files": 182,
      "suppressed": 3,                # pragma-suppressed findings
      "cache_hits": 170,
      "findings": [
        {"pass": "trace-safety", "code": "TS101",
         "path": "paddle_tpu/x.py", "line": 42,
         "message": "...", "hint": "..."}
      ]
    }
"""
from __future__ import annotations

import argparse
import json
import sys


def _parser():
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="trace-safety and registry-parity static analysis for "
                    "the paddle_tpu tree")
    p.add_argument("paths", nargs="*", default=["."],
                   help="files or directories to lint (default: .)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--select", metavar="PASS[,PASS]",
                   help="run only these passes")
    p.add_argument("--disable", metavar="PASS[,PASS]",
                   help="skip these passes")
    p.add_argument("--no-cache", action="store_true",
                   help="ignore and don't write the per-file result cache")
    p.add_argument("--cache", metavar="FILE",
                   help="cache file (default: $GRAFTLINT_CACHE or "
                        "~/.cache/graftlint/cache.json)")
    p.add_argument("--list-passes", action="store_true")
    return p


def _split(s):
    return [x.strip() for x in s.split(",") if x.strip()] if s else None


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    from . import passes as _passes  # noqa: F401 — register built-ins
    from .framework import PASSES, run
    if args.list_passes:
        for name in sorted(PASSES):
            p = PASSES[name]
            scope = "project" if p.project_scope else "file"
            print(f"{name:20s} v{p.version} [{scope}]  {p.description}")
        return 0
    cache = None
    if not args.no_cache:
        from .cache import FileCache
        cache = FileCache(args.cache)
    try:
        result = run(args.paths, select=_split(args.select),
                     disable=_split(args.disable), cache=cache)
    except KeyError as e:
        print(f"graftlint: {e.args[0]}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps({
            "graftlint": 1,
            "passes": result.passes,
            "files": result.files,
            "suppressed": result.suppressed,
            "cache_hits": result.cache_hits,
            "findings": [f.to_dict() for f in result.findings],
        }, indent=2))
    else:
        for f in result.findings:
            print(f.render())
        tail = (f"{len(result.findings)} finding(s) in {result.files} "
                f"file(s); {result.suppressed} suppressed by pragma")
        print(("FAILED: " if result.findings else "OK: ") + tail)
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
