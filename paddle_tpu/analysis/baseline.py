"""Committed-baseline support for incremental adoption of new passes.

A baseline file records the fingerprints of findings that existed when a
pass was introduced; runs loaded with it report only NEW findings, so a
stricter pass can land without first fixing (or pragma-ing) every historical
hit.  The file is JSON, human-reviewable, and meant to be committed:

    {
      "graftlint-baseline": 1,
      "findings": [
        {"fingerprint": "…", "pass": "dtype-rules", "code": "DT102",
         "path": "paddle_tpu/ops/registry.py", "message": "…"}
      ]
    }

Workflow::

    python -m paddle_tpu.analysis paddle_tpu/ --write-baseline .graftlint-baseline.json
    python -m paddle_tpu.analysis paddle_tpu/ --baseline .graftlint-baseline.json

Matching is by :meth:`Finding.fingerprint` (pass, code, repo-relative path,
message — no line number), so edits elsewhere in a file don't resurrect a
baselined finding, while any change to the finding's own message re-surfaces
it for a fresh look.
"""
from __future__ import annotations

import json

from .framework import Finding, norm_path

_SCHEMA = 1


class Baseline:
    """Set of accepted finding fingerprints; ``finding in baseline`` tests
    membership."""

    def __init__(self, fingerprints=()):
        self.fingerprints = set(fingerprints)

    def __contains__(self, finding: Finding) -> bool:
        return finding.fingerprint() in self.fingerprints

    def __len__(self) -> int:
        return len(self.fingerprints)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        """Read a baseline file; missing or corrupt files yield an empty
        baseline (the lint still runs, just without forgiveness)."""
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            if data.get("graftlint-baseline") != _SCHEMA:
                return cls()
            return cls(e["fingerprint"] for e in data.get("findings", ())
                       if "fingerprint" in e)
        except (OSError, ValueError, TypeError):
            return cls()

    @staticmethod
    def write(path: str, findings: list[Finding]) -> int:
        """Write ``findings`` as the new baseline; returns the entry count.
        Entries carry the human-readable context next to the fingerprint so
        reviewers can audit what was accepted."""
        entries = [{"fingerprint": f.fingerprint(), "pass": f.pass_name,
                    "code": f.code, "path": norm_path(f.path),
                    "severity": f.severity, "message": f.message}
                   for f in findings]
        # one entry per fingerprint, sorted for a stable committed diff
        uniq = {e["fingerprint"]: e for e in entries}
        out = {"graftlint-baseline": _SCHEMA,
               "findings": sorted(uniq.values(),
                                  key=lambda e: (e["path"], e["pass"],
                                                 e["code"], e["fingerprint"]))}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
        return len(uniq)
