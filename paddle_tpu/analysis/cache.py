"""Per-file result cache for graftlint.

Keyed on (absolute path, content sha1, pass name, pass version): re-linting an
unchanged tree is pure cache replay.  Project-scope passes (registry-parity,
namespace-parity) are never cached — they depend on cross-file state.

Location: ``$GRAFTLINT_CACHE`` if set, else
``~/.cache/graftlint/cache.json``.  The file is best-effort: unreadable or
corrupt caches are ignored, and write failures never fail the lint run.
"""
from __future__ import annotations

import hashlib
import json
import os

from .framework import Finding

_SCHEMA = 3    # v3: concurrency pass + per-pass rule-ID listings


def default_cache_path():
    env = os.environ.get("GRAFTLINT_CACHE")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "graftlint",
                        "cache.json")


class FileCache:
    def __init__(self, path: str | None = None):
        self.path = path or default_cache_path()
        self._data: dict = {}
        self._dirty = False
        try:
            with open(self.path, encoding="utf-8") as f:
                loaded = json.load(f)
            if loaded.get("schema") == _SCHEMA:
                self._data = loaded.get("files", {})
        except (OSError, ValueError):
            self._data = {}
        self._sha: dict[str, str] = {}

    def _digest(self, src) -> str:
        sha = self._sha.get(src.path)
        if sha is None:
            sha = hashlib.sha1(src.text.encode("utf-8")).hexdigest()
            self._sha[src.path] = sha
        return sha

    def get(self, src, pass_obj) -> list[Finding] | None:
        entry = self._data.get(os.path.abspath(src.path))
        if not entry or entry.get("sha") != self._digest(src):
            return None
        rec = entry.get("passes", {}).get(pass_obj.name)
        if not rec or rec.get("version") != pass_obj.version:
            return None
        return [Finding.from_dict(d) for d in rec.get("findings", [])]

    def put(self, src, pass_obj, findings: list[Finding]):
        key = os.path.abspath(src.path)
        entry = self._data.get(key)
        sha = self._digest(src)
        if not entry or entry.get("sha") != sha:
            entry = self._data[key] = {"sha": sha, "passes": {}}
        entry["passes"][pass_obj.name] = {
            "version": pass_obj.version,
            "findings": [f.to_dict() for f in findings]}
        self._dirty = True

    def save(self):
        if not self._dirty:
            return
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"schema": _SCHEMA, "files": self._data}, f)
            os.replace(tmp, self.path)
        except OSError:
            pass
        self._dirty = False
