"""Per-file result cache for graftlint.

Keyed on (absolute path, content sha1, pass name, pass version): re-linting an
unchanged tree is pure cache replay.  Project-scope passes (registry-parity,
namespace-parity) are never cached — they depend on cross-file state.

Summary-scope passes (contracts) ARE cached, two ways at once: each file
carries a ``summary`` slot (its extracted interprocedural summary, keyed on
content sha + summary schema) and each of the pass's finding records carries
a ``deps`` dict — the per-domain digests of every file contributing facts the
pass consulted.  A hit requires the deps to match the digests of the
*current* tree, so editing ``rpc.py`` invalidates its summary dependents'
entries while edits to fact-free files replay everything else from cache.

Location: ``$GRAFTLINT_CACHE`` if set, else
``~/.cache/graftlint/cache.json``.  The file is best-effort: unreadable or
corrupt caches are ignored, and write failures never fail the lint run.
"""
from __future__ import annotations

import hashlib
import json
import os

from .framework import Finding

_SCHEMA = 4    # v4: interprocedural summary slots + dep-keyed pass entries


def default_cache_path():
    env = os.environ.get("GRAFTLINT_CACHE")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "graftlint",
                        "cache.json")


class FileCache:
    def __init__(self, path: str | None = None):
        self.path = path or default_cache_path()
        self._data: dict = {}
        self._dirty = False
        try:
            with open(self.path, encoding="utf-8") as f:
                loaded = json.load(f)
            if loaded.get("schema") == _SCHEMA:
                self._data = loaded.get("files", {})
        except (OSError, ValueError):
            self._data = {}
        self._sha: dict[str, str] = {}

    def _digest(self, src) -> str:
        sha = self._sha.get(src.path)
        if sha is None:
            sha = hashlib.sha1(src.text.encode("utf-8")).hexdigest()
            self._sha[src.path] = sha
        return sha

    def _entry(self, src, create=False):
        key = os.path.abspath(src.path)
        entry = self._data.get(key)
        sha = self._digest(src)
        if entry is not None and entry.get("sha") == sha:
            return entry
        if not create:
            return None
        entry = self._data[key] = {"sha": sha, "passes": {}}
        return entry

    def get(self, src, pass_obj, deps: dict | None = None) \
            -> list[Finding] | None:
        """Cached findings for ``(src, pass)``; ``deps`` (summary-scope
        passes) must equal the record's stored dep digests — a changed
        cross-file fact domain is a miss even though ``src`` is unchanged."""
        entry = self._entry(src)
        if entry is None:
            return None
        rec = entry.get("passes", {}).get(pass_obj.name)
        if not rec or rec.get("version") != pass_obj.version:
            return None
        if rec.get("deps") != deps:
            return None
        return [Finding.from_dict(d) for d in rec.get("findings", [])]

    def put(self, src, pass_obj, findings: list[Finding],
            deps: dict | None = None):
        rec = {"version": pass_obj.version,
               "findings": [f.to_dict() for f in findings]}
        if deps is not None:
            rec["deps"] = deps
        self._entry(src, create=True)["passes"][pass_obj.name] = rec
        self._dirty = True

    # ---- interprocedural summary slots --------------------------------------
    def get_summary(self, src) -> dict | None:
        from .summaries import SUMMARY_SCHEMA
        entry = self._entry(src)
        if entry is None:
            return None
        slot = entry.get("summary")
        if not slot or slot.get("schema") != SUMMARY_SCHEMA:
            return None
        return slot.get("data")

    def put_summary(self, src, data: dict):
        from .summaries import SUMMARY_SCHEMA
        self._entry(src, create=True)["summary"] = {
            "schema": SUMMARY_SCHEMA, "data": data}
        self._dirty = True

    def save(self):
        if not self._dirty:
            return
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"schema": _SCHEMA, "files": self._data}, f)
            os.replace(tmp, self.path)
        except OSError:
            pass
        self._dirty = False
