"""Lightweight import/alias resolution for project-aware passes.

The SPMD surface is imported under many spellings — ``from jax.sharding
import PartitionSpec as P``, ``from ._compat import shard_map``, ``import
jax`` + ``jax.lax.psum`` — and passes that key on those symbols must see
through every one of them.  :class:`Imports` builds a per-file table mapping
local names to canonical dotted paths (resolving relative imports against
the file's dotted module name when known), and :func:`Imports.canonical`
rewrites any ``Name``/``Attribute`` chain through it.

On top of that sit the symbol classifiers the ``sharding-spec-coverage``
pass uses: :func:`is_shard_map`, :func:`is_partition_spec`,
:func:`collective_axis_arg`, and :func:`mesh_axis_names`.  They match by
canonical-path suffix so both the jax spellings and this repo's wrappers
(``parallel/_compat.shard_map``, ``distributed/collective.mesh_*``) resolve
to the same semantic symbol.
"""
from __future__ import annotations

import ast


class Imports:
    """Local name -> canonical dotted path for one parsed module."""

    def __init__(self, tree: ast.AST, module: str | None = None):
        self.module = module            # dotted name of the analyzed file
        self.aliases: dict[str, str] = {}
        self.star_modules: list[str] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
                    else:               # `import a.b.c` binds only `a`
                        root = a.name.split(".")[0]
                        self.aliases[root] = root
            elif isinstance(node, ast.ImportFrom):
                base = self._from_base(node)
                for a in node.names:
                    if a.name == "*":
                        self.star_modules.append(base)
                        continue
                    target = f"{base}.{a.name}" if base else a.name
                    self.aliases[a.asname or a.name] = target

    def _from_base(self, node: ast.ImportFrom) -> str:
        mod = node.module or ""
        if not node.level:
            return mod
        if self.module:
            parts = self.module.split(".")[:-node.level]
            return ".".join(parts + mod.split(".")) if mod \
                else ".".join(parts)
        return mod                      # relative, module unknown: keep tail

    def canonical(self, node) -> str | None:
        """Canonical dotted path of a ``Name``/``Attribute`` chain, with the
        root name rewritten through the import table; None for anything
        else (calls, subscripts, ...)."""
        attrs = []
        while isinstance(node, ast.Attribute):
            attrs.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        return ".".join([root] + list(reversed(attrs)))


def _match(canon: str | None, suffixes) -> bool:
    if not canon:
        return False
    return any(canon == s or canon.endswith("." + s) for s in suffixes)


# every spelling that means jax's shard_map, including this repo's shim
_SHARD_MAP = ("jax.shard_map", "jax.experimental.shard_map.shard_map",
              "parallel._compat.shard_map", "_compat.shard_map", "shard_map")
_PARTITION_SPEC = ("jax.sharding.PartitionSpec",
                   "jax.experimental.pjit.PartitionSpec", "PartitionSpec")
_NAMED_SHARDING = ("jax.sharding.NamedSharding", "NamedSharding")
# jit entry points that accept in_shardings=/out_shardings= keywords
_JIT = ("jax.jit", "pjit")
# canonical-path suffix -> positional index of the axis-name argument
_COLLECTIVES = {
    "lax.psum": 1, "lax.pmean": 1, "lax.pmax": 1, "lax.pmin": 1,
    "lax.ppermute": 1, "lax.pshuffle": 1, "lax.all_gather": 1,
    "lax.all_to_all": 1, "lax.psum_scatter": 1, "lax.axis_index": 0,
    "collective.mesh_all_reduce": 1, "collective.mesh_all_gather": 1,
    "collective.mesh_reduce_scatter": 1, "collective.mesh_all_to_all": 1,
    "collective.mesh_ppermute": 1,
}
# mesh constructors -> positional index of the axis-names argument
_MESH_CTORS = {"jax.sharding.Mesh": 1, "jax.make_mesh": 1, "Mesh": 1}


def is_shard_map(canon: str | None) -> bool:
    return _match(canon, _SHARD_MAP)


def is_partition_spec(canon: str | None) -> bool:
    return _match(canon, _PARTITION_SPEC)


def is_named_sharding(canon: str | None) -> bool:
    return _match(canon, _NAMED_SHARDING)


def is_jit(canon: str | None) -> bool:
    return _match(canon, _JIT)


def collective_axis_arg(canon: str | None):
    """Positional index of the collective's axis-name argument, or None if
    ``canon`` is not a recognized collective."""
    if not canon:
        return None
    for suffix, idx in _COLLECTIVES.items():
        if canon == suffix or canon.endswith("." + suffix):
            return idx
    return None


def _literal_axis_names(node) -> list[str] | None:
    """Axis names from a literal str / tuple / list of strs, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        names = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            names.append(e.value)
        return names
    return None


def mesh_axis_names(call: ast.Call, imports: Imports) -> list[str] | None:
    """Axis names of a mesh-constructor call when they are literal —
    ``Mesh(devices, ("dp", "mp"))`` / ``jax.make_mesh((2, 2), ("dp", "mp"))``
    — else None."""
    canon = imports.canonical(call.func)
    for suffix, idx in _MESH_CTORS.items():
        if canon == suffix or (canon and canon.endswith("." + suffix)):
            node = call.args[idx] if len(call.args) > idx else None
            if node is None:
                for kw in call.keywords:
                    if kw.arg == "axis_names":
                        node = kw.value
            return _literal_axis_names(node) if node is not None else None
    return None
