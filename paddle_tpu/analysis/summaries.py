"""Interprocedural summaries — the whole-program half of graftlint.

Per-file passes see one AST at a time, but the fleet's worst bugs live
*between* files: an RPC op string emitted in ``fleet.py`` must meet an
``op == "..."`` comparison in ``worker.py``; a fault point fired in
``engine/core.py`` must be declared in ``testing/faults.py`` and armed by
some ``injected(...)`` in ``tests/``.  This module extracts a small,
JSON-serializable **summary** from every file (functions and their
parameters, calls whose first argument is a constant string or a forwarded
parameter, dispatcher registrations, exception classes, metric-family and
fault-point facts) and folds them into a :class:`SummaryIndex` — the fact
tables summary-scope passes query.

Caching/invalidation contract (cache schema v4):

* each file's summary is cached next to its per-pass findings, keyed on the
  file's content sha and :data:`SUMMARY_SCHEMA`;
* each *domain* of facts (``rpc``, ``exceptions``, ``faults``, ``metrics``)
  has a **digest** over the ``(path, sha)`` pairs of every file that
  contributes facts to it;
* a summary-scope pass's cache entries record the digests of the domains it
  consults.  Editing ``rpc.py`` (an rpc-domain contributor) changes that
  digest and re-lints every dependent file; editing a file with no rpc
  facts leaves the digest — and every other file's cache entry — intact.
"""
from __future__ import annotations

import ast
import builtins
import hashlib

from .framework import Project, norm_path
from .resolve import Imports, _match

SUMMARY_SCHEMA = 1

# canonical-path suffixes that mean "a fault-injector probe"
_FAULT_FIRE_SUFFIXES = ("FAULTS.fire", "FAULTS.raise_if", "FAULTS.maybe_fire")
_FAULT_COVER_SUFFIXES = ("FAULTS.install", "faults.injected",
                         "testing.injected", "injected")
_METRIC_KINDS = ("counter", "gauge", "histogram")

_BUILTIN_EXCEPTIONS = frozenset(
    n for n in dir(builtins)
    if isinstance(getattr(builtins, n), type)
    and issubclass(getattr(builtins, n), BaseException))


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_client_receiver(expr):
    """Same lexical client heuristic as the no-adhoc-telemetry pass:
    ``client.call`` / ``self.client.call`` / ``foo_client.call`` /
    ``rpc.call``."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return False
    name = name.lower().lstrip("_")
    return name == "rpc" or name == "client" or name.endswith("_client")


def _registry_receiver(imports, expr):
    """True when ``expr`` is the metrics registry (``REGISTRY.counter`` /
    ``_registry.REGISTRY.counter`` under any import spelling)."""
    canon = imports.canonical(expr)
    return bool(canon) and (canon == "REGISTRY" or canon.endswith(".REGISTRY"))


def _value_params(params, is_method):
    """The parameters that carry caller values (drop self/cls)."""
    return params[1:] if is_method and params else params


class _Extractor(ast.NodeVisitor):
    """One pass over a module AST, tracking class/function nesting."""

    def __init__(self, tree, module):
        self.imports = Imports(tree, module)
        self.class_stack: list[str] = []
        self.func_stack: list[str] = []
        # names bound from an RpcClient(...) constructor — `c = RpcClient(
        # host, port); c.call("op")` is an op site even though `c` is not a
        # lexically client-ish name
        self.client_vars: set[str] = set()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)
                    and _match(self.imports.canonical(node.value.func),
                               ("RpcClient",))):
                tgt = node.targets[0]
                name = tgt.id if isinstance(tgt, ast.Name) else (
                    tgt.attr if isinstance(tgt, ast.Attribute) else None)
                if name:
                    self.client_vars.add(name)
        # qualname -> {"params", "method", "ends_raise", "eq": {param: [...]}}
        self.functions: dict[str, dict] = {}
        self.call_records: list[dict] = []
        self.dispatchers: list[dict] = []
        self.metric_decls: list[dict] = []
        self.fault_fires: list[dict] = []
        self.fault_coverage: list[dict] = []
        self.fault_decls: list[dict] = []
        self.classes: dict[str, dict] = {}
        self.raises: list[dict] = []
        self.imported: list[str] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                self.imported.extend(a.name for a in node.names)
            elif isinstance(node, ast.ImportFrom):
                base = self.imports._from_base(node)
                if base:
                    self.imported.append(base)
                    self.imported.extend(f"{base}.{a.name}" for a in node.names
                                         if a.name != "*")
        self.visit(tree)

    # ---- scope tracking ------------------------------------------------------
    def _qual(self, name):
        return ".".join(self.class_stack + self.func_stack + [name])

    def visit_ClassDef(self, node):
        self._record_class(node)
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node):
        params = [a.arg for a in node.args.posonlyargs + node.args.args]
        is_method = bool(self.class_stack) and not self.func_stack and bool(
            params) and not any(
                isinstance(d, ast.Name) and d.id == "staticmethod"
                for d in node.decorator_list)
        qual = self._qual(node.name)
        body = node.body
        self.functions[qual] = {
            "params": params, "method": is_method, "line": node.lineno,
            "ends_raise": bool(body) and isinstance(body[-1], ast.Raise),
            "eq": self._eq_strings(node, params),
        }
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    @staticmethod
    def _eq_strings(func, params):
        """``param == "lit"`` / ``param in ("a", "b")`` comparisons in
        ``func``'s own body (nested defs summarize separately)."""
        out: dict[str, list] = {}
        pset = set(params)
        for node in ast.walk(func):
            if not isinstance(node, ast.Compare) or len(node.ops) != 1:
                continue
            left, op, right = node.left, node.ops[0], node.comparators[0]
            if not (isinstance(left, ast.Name) and left.id in pset):
                continue
            lits = []
            if isinstance(op, ast.Eq):
                s = _const_str(right)
                if s is not None:
                    lits = [s]
            elif isinstance(op, ast.In) and isinstance(
                    right, (ast.Tuple, ast.List, ast.Set)):
                lits = [s for s in map(_const_str, right.elts)
                        if s is not None]
            for s in lits:
                out.setdefault(left.id, []).append([s, node.lineno])
        return out

    # ---- fact extraction -----------------------------------------------------
    def visit_Assign(self, node):
        # module-level KNOWN_POINTS = frozenset({...}) fault-point table
        if (not self.class_stack and not self.func_stack
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "KNOWN_POINTS"):
            val = node.value
            if (isinstance(val, ast.Call) and isinstance(val.func, ast.Name)
                    and val.func.id in ("frozenset", "set") and val.args):
                val = val.args[0]
            if isinstance(val, (ast.Set, ast.Tuple, ast.List)):
                names = [s for s in map(_const_str, val.elts) if s is not None]
                if names:
                    self.fault_decls.append(
                        {"names": names, "line": node.lineno})
        self.generic_visit(node)

    def visit_Raise(self, node):
        exc = node.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        canon = self.imports.canonical(exc) if exc is not None else None
        if canon:
            self.raises.append({"name": canon, "line": node.lineno})
        self.generic_visit(node)

    def visit_Call(self, node):
        self._record_call(node)
        self.generic_visit(node)

    def _enclosing(self):
        if not self.func_stack:
            return None, None
        qual = ".".join(self.class_stack + self.func_stack)
        return qual, self.functions.get(qual)

    def _record_call(self, node):
        func = node.func
        canon = self.imports.canonical(func)
        arg0 = node.args[0] if node.args else None
        lit = _const_str(arg0) if arg0 is not None else None
        line = node.lineno

        # metric-family declarations on the registry
        if (isinstance(func, ast.Attribute) and func.attr in _METRIC_KINDS
                and _registry_receiver(self.imports, func.value)):
            name = lit
            if name is None:
                for kw in node.keywords:
                    if kw.arg == "name":
                        name = _const_str(kw.value)
            literal = lit is not None or name is not None
            self.metric_decls.append({"kind": func.attr, "metric": name,
                                      "literal": literal, "line": line})

        # fault-injector probes and chaos coverage
        if _match(canon, _FAULT_FIRE_SUFFIXES):
            self.fault_fires.append(
                {"api": canon.rsplit(".", 1)[-1], "point": lit, "line": line})
        elif _match(canon, _FAULT_COVER_SUFFIXES) and lit is not None:
            self.fault_coverage.append({"point": lit, "line": line})

        # dispatcher registration: RpcServer(handler, ...)
        if _match(canon, ("RpcServer",)) and node.args:
            handler = node.args[0]
            ref = None
            if (isinstance(handler, ast.Attribute)
                    and isinstance(handler.value, ast.Name)
                    and handler.value.id == "self" and self.class_stack):
                ref = {"kind": "method", "cls": self.class_stack[-1],
                       "name": handler.attr}
            elif isinstance(handler, ast.Name):
                ref = {"kind": "func", "name": handler.id,
                       "scope": ".".join(self.class_stack + self.func_stack)}
            elif isinstance(handler, ast.Lambda):
                params = [a.arg for a in handler.args.args]
                eq = self._eq_strings(handler, params)
                ops = eq.get(params[0], []) if params else []
                ref = {"kind": "inline", "ops": ops}
            if ref is not None:
                ref["line"] = line
                self.dispatchers.append(ref)

        # first-arg tracking for RPC op parity (CT101): constant-string and
        # forwarded-parameter arg0 calls on clients / self-methods / dotted
        # callees
        enc_qual, enc = self._enclosing()
        arg0_kind, arg0_val = None, None
        if lit is not None:
            arg0_kind, arg0_val = "str", lit
        elif (isinstance(arg0, ast.Name) and enc is not None
              and arg0.id in enc["params"]):
            arg0_kind, arg0_val = "param", arg0.id
        if arg0_kind is None:
            return
        if (isinstance(func, ast.Attribute) and func.attr == "call"
                and (_is_client_receiver(func.value)
                     or self._is_client_var(func.value))):
            callee_kind, callee_key = "client", "call"
        elif (isinstance(func, ast.Attribute)
              and isinstance(func.value, ast.Name)
              and func.value.id == "self" and self.class_stack):
            callee_kind = "self"
            callee_key = f"{self.class_stack[-1]}.{func.attr}"
        elif canon:
            callee_kind, callee_key = "dotted", canon
        else:
            return
        self.call_records.append(
            {"enc": enc_qual, "callee_kind": callee_kind,
             "callee": callee_key, "arg0_kind": arg0_kind,
             "arg0": arg0_val, "line": line})

    def _is_client_var(self, expr):
        """Receiver was bound from ``RpcClient(...)`` somewhere in this
        module (``c = RpcClient(h, p); c.call("op")``)."""
        if isinstance(expr, ast.Name):
            return expr.id in self.client_vars
        if isinstance(expr, ast.Attribute):
            return expr.attr in self.client_vars
        return False

    def _record_class(self, node):
        bases = [c for c in (self.imports.canonical(b) for b in node.bases)
                 if c]
        has_reduce = any(
            isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name in ("__reduce__", "__reduce_ex__")
            for n in node.body)
        init = next((n for n in node.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        init_safe, init_line = True, node.lineno
        if init is not None:
            init_line = init.lineno
            init_safe = self._init_forwards_args(init)
        qual = ".".join(self.class_stack + [node.name])
        self.classes[qual] = {
            "name": node.name, "line": node.lineno, "bases": bases,
            "has_reduce": has_reduce, "init_safe": init_safe,
            "init_line": init_line}

    @staticmethod
    def _init_forwards_args(init):
        """True when ``__init__`` re-raisable by value: every declared
        parameter is forwarded verbatim, in order, as a positional argument
        of ``super().__init__`` (the default ``__reduce__`` replays
        ``cls(*self.args)``, so args must round-trip)."""
        params = [a.arg for a in init.args.posonlyargs + init.args.args][1:]
        required = len(params) - len(init.args.defaults)
        if any(d is None for d in init.args.kw_defaults):
            return False                 # required kw-only: cls(*args) fails
        for node in ast.walk(init):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "__init__"
                    and isinstance(node.func.value, ast.Call)
                    and isinstance(node.func.value.func, ast.Name)
                    and node.func.value.func.id == "super"):
                pos = []
                for a in node.args:
                    if isinstance(a, ast.Name):
                        pos.append(a.id)
                    elif (isinstance(a, ast.Starred)
                          and isinstance(a.value, ast.Name)):
                        pos.append("*" + a.value.id)
                    else:
                        return False
                want = list(params)
                if init.args.vararg is not None:
                    want.append("*" + init.args.vararg.arg)
                # a verbatim in-order prefix covering every required param
                # round-trips: the default __reduce__ replays cls(*self.args)
                return pos == want[:len(pos)] and len(pos) >= required
        # no super().__init__ call: BaseException.__new__ already stored the
        # constructor args verbatim, so the default __reduce__ round-trips
        return True


def summarize(src, module=None) -> dict:
    """Extract ``src``'s JSON-serializable module summary."""
    if module is None:
        module = Project.module_name(src.path)
    ex = _Extractor(src.tree, module)
    return {
        "schema": SUMMARY_SCHEMA,
        "module": module,
        "functions": ex.functions,
        "calls": ex.call_records,
        "dispatchers": ex.dispatchers,
        "metric_decls": ex.metric_decls,
        "fault_fires": ex.fault_fires,
        "fault_coverage": ex.fault_coverage,
        "fault_decls": ex.fault_decls,
        "classes": ex.classes,
        "raises": ex.raises,
        "imports": sorted(set(ex.imported)),
    }


class SummaryIndex:
    """Project-wide fact tables folded from per-file summaries.

    Construction consults (and fills) the :class:`~.cache.FileCache`'s
    summary slots when a cache is given; the per-domain digests it computes
    are what summary-scope passes record as their cache dependencies.
    """

    DOMAINS = ("rpc", "exceptions", "faults", "metrics")

    def __init__(self, project: Project, cache=None):
        self.project = project
        self.summaries: dict[str, dict] = {}
        self._sha: dict[str, str] = {}
        for f in project.files:
            data = cache.get_summary(f) if cache is not None else None
            if data is None:
                data = summarize(f)
                if cache is not None:
                    cache.put_summary(f, data)
            self.summaries[f.path] = data
            self._sha[f.path] = hashlib.sha1(
                f.text.encode("utf-8")).hexdigest()
        self._build_functions()
        self._build_rpc()
        self._build_faults()
        self._build_metrics()
        self._build_exceptions()
        self._digests = {d: self._digest(c) for d, c in (
            ("rpc", self._rpc_contributors),
            ("exceptions", self._exc_contributors),
            ("faults", self._fault_contributors),
            ("metrics", self._metric_contributors))}

    # ---- dependency digests --------------------------------------------------
    def _digest(self, paths) -> str:
        lines = sorted(f"{norm_path(p)}:{self._sha[p]}" for p in paths)
        return hashlib.sha1("\n".join(lines).encode("utf-8")).hexdigest()[:16]

    def domain_digest(self, domain: str) -> str:
        return self._digests[domain]

    def pass_deps(self, pass_obj) -> dict:
        """The dep record a summary pass's cache entries carry: schema plus
        one digest per consulted fact domain."""
        deps = {"summary_schema": SUMMARY_SCHEMA}
        for d in getattr(pass_obj, "summary_domains", ()) or self.DOMAINS:
            deps[d] = self._digests[d]
        return deps

    # ---- function / forwarder resolution -------------------------------------
    def _build_functions(self):
        # (path, qualname) function table + name indexes for resolution
        self.functions: dict[tuple, dict] = {}
        self._by_method: dict[str, list] = {}     # "Cls.meth" -> [(path, qual)]
        self._by_name: dict[str, list] = {}       # trailing name -> [...]
        for path, s in self.summaries.items():
            for qual, fn in s["functions"].items():
                self.functions[(path, qual)] = fn
                name = qual.rsplit(".", 1)[-1]
                self._by_name.setdefault(name, []).append((path, qual))
                if fn["method"] and "." in qual:
                    cls = qual.rsplit(".", 2)[-2]
                    self._by_method.setdefault(
                        f"{cls}.{name}", []).append((path, qual))

    def _resolve_callee(self, path, rec):
        """Resolve a call record's callee to a (path, qualname) function key,
        preferring same-file definitions; None when unknown."""
        kind, key = rec["callee_kind"], rec["callee"]
        if kind == "client":
            return None
        if kind == "self":
            cands = self._by_method.get(key, [])
        else:                                   # dotted canonical
            name = key.rsplit(".", 1)[-1]
            cands = [c for c in self._by_name.get(name, [])
                     if self._dotted_matches(c, key)]
        if not cands:
            return None
        same = [c for c in cands if c[0] == path]
        return (same or cands)[0]

    def _dotted_matches(self, cand, canon):
        path, qual = cand
        mod = self.summaries[path]["module"]
        full = f"{mod}.{qual}" if mod else qual
        return full == canon or full.endswith("." + canon) \
            or canon.endswith("." + qual) or canon == qual

    def _first_value_param(self, key):
        fn = self.functions[key]
        vp = _value_params(fn["params"], fn["method"])
        return vp[0] if vp else None

    # ---- rpc domain ----------------------------------------------------------
    def _build_rpc(self):
        # forwarders: functions whose first value param flows into a client
        # call's (or another forwarder's) first argument — fixpoint so
        # multi-hop forwarding chains resolve
        forwarders: set = set()
        recs = [(path, rec) for path, s in self.summaries.items()
                for rec in s["calls"]]
        changed = True
        while changed:
            changed = False
            for path, rec in recs:
                if rec["arg0_kind"] != "param" or rec["enc"] is None:
                    continue
                enc_key = (path, rec["enc"])
                if enc_key in forwarders or enc_key not in self.functions:
                    continue
                if rec["arg0"] != self._first_value_param(enc_key):
                    continue
                if rec["callee_kind"] == "client":
                    forwarders.add(enc_key)
                    changed = True
                else:
                    callee = self._resolve_callee(path, rec)
                    if callee in forwarders:
                        forwarders.add(enc_key)
                        changed = True
        self.forwarders = forwarders

        # op sites: constant-string first args reaching a client call,
        # directly or through a forwarder
        self.op_sites: list[tuple] = []           # (path, line, op)
        for path, rec in recs:
            if rec["arg0_kind"] != "str":
                continue
            if rec["callee_kind"] == "client" or \
                    self._resolve_callee(path, rec) in self.forwarders:
                self.op_sites.append((path, rec["line"], rec["arg0"]))

        # dispatchers resolved to their op tables
        self.dispatchers: list[dict] = []         # {path,line,ops,closed}
        for path, s in self.summaries.items():
            for d in s["dispatchers"]:
                if d["kind"] == "inline":
                    self.dispatchers.append(
                        {"path": path, "line": d["line"], "ops": d["ops"],
                         "closed": False})
                    continue
                if d["kind"] == "method":
                    key = f"{d['cls']}.{d['name']}"
                    cands = [c for c in self._by_method.get(key, [])
                             if c[0] == path] or self._by_method.get(key, [])
                else:
                    cands = [c for c in self._by_name.get(d["name"], [])
                             if c[0] == path]
                    scope = d.get("scope", "")
                    if len(cands) > 1 and scope:
                        inner = [c for c in cands
                                 if c[1].startswith(scope + ".")]
                        cands = inner or cands
                if not cands:
                    continue
                key = cands[0]
                fn = self.functions[key]
                op_param = self._first_value_param(key)
                ops = fn["eq"].get(op_param, []) if op_param else []
                self.dispatchers.append(
                    {"path": key[0], "line": d["line"], "ops": ops,
                     "closed": fn["ends_raise"]})
        self.handled_ops: dict[str, list] = {}
        for d in self.dispatchers:
            for op, line in d["ops"]:
                self.handled_ops.setdefault(op, []).append((d["path"], line))
        self.open_dispatcher_paths = {d["path"] for d in self.dispatchers
                                      if not d["closed"]}
        self._rpc_contributors = (
            {p for p, r in recs
             if r["callee_kind"] == "client"
             or (p, r["enc"]) in self.forwarders
             or self._resolve_callee(p, r) in self.forwarders}
            | {d["path"] for d in self.dispatchers}
            | {k[0] for k in self.forwarders}
            | {p for p, s in self.summaries.items() if s["dispatchers"]})

    # ---- faults domain -------------------------------------------------------
    def _build_faults(self):
        self.fault_decls: list[tuple] = []        # (path, line, names)
        self.fault_fires: list[tuple] = []        # (path, line, api, point)
        self.fault_coverage: set = set()
        self._fault_contributors = set()
        for path, s in self.summaries.items():
            for d in s["fault_decls"]:
                self.fault_decls.append((path, d["line"], d["names"]))
            for f in s["fault_fires"]:
                self.fault_fires.append(
                    (path, f["line"], f["api"], f["point"]))
            for c in s["fault_coverage"]:
                self.fault_coverage.add(c["point"])
            if s["fault_decls"] or s["fault_fires"] or s["fault_coverage"]:
                self._fault_contributors.add(path)
        self.declared_points = {n for _, _, names in self.fault_decls
                                for n in names}
        self.decl_paths = {p for p, _, _ in self.fault_decls}
        self.has_fault_coverage = any(
            s["fault_coverage"] for s in self.summaries.values())
        self.has_outside_fires = any(
            p not in self.decl_paths for p, _, _, _ in self.fault_fires)

    # ---- metrics domain ------------------------------------------------------
    def _build_metrics(self):
        self.metric_decls: list[dict] = []
        self._metric_contributors = set()
        for path, s in self.summaries.items():
            for m in s["metric_decls"]:
                self.metric_decls.append(dict(m, path=path))
            if s["metric_decls"]:
                self._metric_contributors.add(path)
        # first declaration wins the family's type; later conflicts flag
        self.metric_decls.sort(key=lambda m: (norm_path(m["path"]),
                                              m["line"]))
        self.metric_kinds: dict[str, dict] = {}
        for m in self.metric_decls:
            if m["metric"] is not None:
                self.metric_kinds.setdefault(m["metric"], m)

    # ---- exceptions domain ---------------------------------------------------
    def _build_exceptions(self):
        # which project classes are exceptions (fixpoint over base chains)
        self.classes: dict[tuple, dict] = {}      # (path, qual) -> info
        by_name: dict[str, list] = {}
        for path, s in self.summaries.items():
            for qual, c in s["classes"].items():
                self.classes[(path, qual)] = c
                by_name.setdefault(c["name"], []).append((path, qual))
        exceptional: set = set()
        changed = True
        while changed:
            changed = False
            for key, c in self.classes.items():
                if key in exceptional:
                    continue
                for b in c["bases"]:
                    tail = b.rsplit(".", 1)[-1]
                    if tail in _BUILTIN_EXCEPTIONS or any(
                            k in exceptional for k in by_name.get(tail, [])):
                        exceptional.add(key)
                        changed = True
                        break
        self.exception_classes = exceptional

        # transitive project-module closure from every dispatcher module
        mod_paths: dict[str, str] = {}
        for path, s in self.summaries.items():
            if s["module"]:
                mod_paths[s["module"]] = path
        closure: set = {d["path"] for d in self.dispatchers}
        frontier = list(closure)
        while frontier:
            path = frontier.pop()
            for target in self.summaries[path]["imports"]:
                hit = mod_paths.get(target) or \
                    mod_paths.get(target.rsplit(".", 1)[0])
                if hit is not None and hit not in closure:
                    closure.add(hit)
                    frontier.append(hit)
        self.dispatch_closure = closure

        # exception classes raised anywhere in the closure, resolved to
        # their defining file (same-file first, then by class name)
        self.raised_in_closure: set = set()
        for path in closure:
            for r in self.summaries[path]["raises"]:
                name = r["name"].rsplit(".", 1)[-1]
                cands = by_name.get(name, [])
                same = [c for c in cands if c[0] == path]
                for key in (same or cands)[:1]:
                    self.raised_in_closure.add(key)
        self._exc_contributors = (
            closure | {k[0] for k in exceptional}
            | {p for p, s in self.summaries.items() if s["raises"]})

    @property
    def has_dispatchers(self):
        return bool(self.dispatchers)

    @property
    def has_op_sites(self):
        return bool(self.op_sites)
