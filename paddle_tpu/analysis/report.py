"""Report serializers for CI annotation: the graftlint JSON schema and
SARIF 2.1.0 (the format GitHub code scanning, VS Code SARIF viewers, and
most CI annotators ingest).

SARIF mapping: each pass's finding codes become ``rules`` on the single
``graftlint`` driver; ``severity`` maps to SARIF ``level`` (error/warning);
locations carry the path as a relative URI plus the 1-based start line.
"""
from __future__ import annotations

import os

from .framework import RunResult

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                 "master/Schemata/sarif-schema-2.1.0.json")
_SARIF_VERSION = "2.1.0"


def to_json(result: RunResult) -> dict:
    """The ``--format json`` schema (see cli.py docstring)."""
    return {
        "graftlint": 1,
        "passes": result.passes,
        "files": result.files,
        "suppressed": result.suppressed,
        "baselined": result.baselined,
        "cache_hits": result.cache_hits,
        "findings": [f.to_dict() for f in result.findings],
    }


def _uri(path: str) -> str:
    """Forward-slash relative URI for SARIF artifactLocation."""
    rel = os.path.relpath(path) if os.path.isabs(path) else path
    if rel.startswith(".."):            # outside cwd: keep it absolute
        rel = os.path.abspath(path)
    return rel.replace(os.sep, "/")


def to_sarif(result: RunResult) -> dict:
    """SARIF 2.1.0 log with one run and one rule per finding code."""
    rules = {}
    for f in result.findings:
        if f.code not in rules:
            rules[f.code] = {
                "id": f.code,
                "name": f.pass_name,
                "shortDescription": {"text": f"[{f.pass_name}] {f.code}"},
                "defaultConfiguration": {"level": f.severity},
            }
            if f.hint:
                rules[f.code]["help"] = {"text": f.hint}
    rule_ids = sorted(rules)
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for f in result.findings:
        text = f.message + (f"  [fix: {f.hint}]" if f.hint else "")
        results.append({
            "ruleId": f.code,
            "ruleIndex": rule_index[f.code],
            "level": f.severity,
            "message": {"text": text},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": _uri(f.path)},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
            "fingerprints": {"graftlint/v1": f.fingerprint()},
        })
    return {
        "$schema": _SARIF_SCHEMA,
        "version": _SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "https://github.com/paddle-tpu/paddle-tpu",
                "rules": [rules[rid] for rid in rule_ids],
            }},
            "results": results,
            "columnKind": "utf16CodeUnits",
        }],
    }
