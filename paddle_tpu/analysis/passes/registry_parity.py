"""registry-parity — keep the declarative op table honest.

The whole op surface is driven by one table (``ops/registry.py``, the
ops.yaml analog), so drift there is invisible until a runtime test happens to
hit the broken entry.  This pass cross-checks every entry:

  * RP001 duplicate registration (later entry silently shadows the earlier)
  * RP002 unknown category (not in ``registry.CATEGORIES``)
  * RP003 ``kind="golden"`` without a numpy reference or property check
  * RP004 unknown ``kind``
  * RP005 alias/inplace target does not resolve
  * RP006 resolver missing (the public op the entry points at doesn't exist)
  * RP007 resolver arity incompatible with the sample builder + kwargs
  * RP008 sample builder raises

Static checks run on any module that registers ops through the canonical
helpers (``u``/``b``/``g``/``smoke``/``alias``/``inplace``); the runtime
checks additionally import the module and inspect the live ``REGISTRY`` when
the file belongs to an importable package.
"""
from __future__ import annotations

import ast
import importlib
import inspect

from ..framework import AnalysisPass, Finding, Project, register_pass

_HELPERS = {"u", "b", "g", "smoke", "alias", "inplace"}
# helper -> positional index / keyword of its category argument
_CAT_ARG = {"u": (None, "cat"), "b": (None, "cat"), "g": (3, "cat"),
            "smoke": (2, "cat"), "alias": (2, "cat"), "inplace": (2, "cat")}
_FALLBACK_CATEGORIES = {
    "math", "reduce", "linalg", "logic", "manip", "search", "stat",
    "creation", "random", "fft", "signal", "inplace"}
_KINDS = {"golden", "smoke", "alias", "inplace"}


def _const(node):
    return node.value if isinstance(node, ast.Constant) else None


def _kwarg(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class _RegCall:
    def __init__(self, helper, call):
        self.helper = helper
        self.call = call
        self.line = call.lineno
        self.name = _const(call.args[0]) if call.args else None

    def category(self):
        pos, kw = _CAT_ARG[self.helper]
        node = _kwarg(self.call, kw)
        if node is None and pos is not None and len(self.call.args) > pos:
            node = self.call.args[pos]
        return _const(node) if node is not None else None


@register_pass
class RegistryParityPass(AnalysisPass):
    name = "registry-parity"
    version = 1
    codes = ("RP001", "RP002", "RP003", "RP004", "RP005",
             "RP006", "RP007", "RP008")
    description = ("op-registry consistency: resolver existence/arity, "
                   "golden references, duplicate names, categories")
    project_scope = True    # runtime half imports the live registry

    def check_project(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for src in project.files:
            regs = self._collect(src)
            if not regs:
                continue
            categories = _FALLBACK_CATEGORIES
            mod = Project.module_name(src.path)
            live = None
            if mod is not None:
                try:
                    live = importlib.import_module(mod)
                    categories = getattr(live, "CATEGORIES", categories)
                except Exception as e:   # import failure IS a finding
                    findings.append(Finding(
                        self.name, "RP006", src.path, 1,
                        f"registry module {mod!r} failed to import: "
                        f"{type(e).__name__}: {e}"))
            findings.extend(self._static(src, regs, categories))
            if live is not None and hasattr(live, "REGISTRY"):
                lines = {r.name: r.line for r in regs if r.name}
                findings.extend(self._runtime(src, live, lines, categories))
        return findings

    # ---- static half -----------------------------------------------------
    def _collect(self, src):
        # only treat a file as a registry if it touches the canonical table
        mentions = {n.id for n in ast.walk(src.tree)
                    if isinstance(n, ast.Name)}
        if not {"REGISTRY", "OpSpec"} & mentions:
            return []
        regs = []
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                    and node.func.id in _HELPERS and node.args
                    and isinstance(_const(node.args[0]), str)):
                regs.append(_RegCall(node.func.id, node))
        return regs

    def _static(self, src, regs, categories):
        findings = []
        seen: dict[str, int] = {}
        for r in regs:
            if r.name in seen:
                findings.append(Finding(
                    self.name, "RP001", src.path, r.line,
                    f"duplicate registration of op '{r.name}' (first at "
                    f"line {seen[r.name]}) — the earlier entry is silently "
                    "shadowed",
                    hint="rename one entry or delete the stale duplicate"))
            else:
                seen[r.name] = r.line
            cat = r.category()
            if cat is not None and cat not in categories:
                findings.append(Finding(
                    self.name, "RP002", src.path, r.line,
                    f"op '{r.name}' registered under unknown category "
                    f"'{cat}'",
                    hint=f"use one of: {', '.join(sorted(categories))}"))
            if r.helper in ("u", "b", "g"):
                ref = r.call.args[1] if len(r.call.args) > 1 else None
                kind = _const(_kwarg(r.call, "kind") or ast.Constant("golden"))
                if (isinstance(ref, ast.Constant) and ref.value is None
                        and _kwarg(r.call, "check") is None
                        and kind == "golden"):
                    findings.append(Finding(
                        self.name, "RP003", src.path, r.line,
                        f"golden op '{r.name}' has neither np_ref nor a "
                        "property check — nothing verifies its output",
                        hint="add np_ref/check, or register it as "
                             "kind=\"smoke\" with a reason"))
        return findings

    # ---- runtime half ----------------------------------------------------
    def _runtime(self, src, live, lines, categories):
        findings = []

        def emit(name, code, msg, hint=""):
            findings.append(Finding(self.name, code, src.path,
                                    lines.get(name, 1), msg, hint))

        for rec in getattr(live, "DUPLICATE_REGISTRATIONS", ()):
            emit(rec, "RP001",
                 f"duplicate registration of op '{rec}' observed at import "
                 "time — the earlier entry is silently shadowed",
                 "rename one entry or delete the stale duplicate")
        for name, spec in live.REGISTRY.items():
            if spec.kind not in _KINDS:
                emit(name, "RP004", f"op '{name}' has unknown kind "
                     f"'{spec.kind}'")
                continue
            if spec.category not in categories:
                emit(name, "RP002", f"op '{name}' registered under unknown "
                     f"category '{spec.category}'",
                     f"use one of: {', '.join(sorted(categories))}")
            if spec.kind in ("alias", "inplace"):
                base = live.REGISTRY.get(spec.alias_of)
                target = spec.alias_of if spec.kind == "alias" else name
                try:
                    import paddle_tpu.ops as O
                    ok = callable(getattr(O, target, None))
                    if spec.kind == "inplace" and not ok:
                        from paddle_tpu.core.tensor import Tensor
                        ok = callable(getattr(Tensor, name, None))
                except Exception:
                    ok = False
                if base is None and not ok:
                    emit(name, "RP005",
                         f"{spec.kind} op '{name}' points at "
                         f"'{spec.alias_of}', which neither the registry nor "
                         "the op surface resolves",
                         "fix alias_of or register the base op")
                continue
            if spec.kind == "golden" and spec.np_ref is None \
                    and spec.check is None:
                emit(name, "RP003",
                     f"golden op '{name}' has neither np_ref nor a property "
                     "check — nothing verifies its output",
                     "add np_ref/check, or register it as kind=\"smoke\" "
                     "with a reason")
            try:
                resolver = spec.resolve()
            except Exception as e:
                emit(name, "RP006",
                     f"op '{name}' resolver is missing "
                     f"({type(e).__name__}: {e})",
                     "export the op or point the entry's `op` at the "
                     "right target")
                continue
            try:
                sample = spec.sample() if spec.sample else []
            except Exception as e:
                emit(name, "RP008",
                     f"op '{name}' sample builder raised "
                     f"{type(e).__name__}: {e}")
                continue
            self._check_arity(emit, name, resolver, len(sample),
                              set(spec.kwargs))
        return findings

    @staticmethod
    def _check_arity(emit, name, resolver, n_inputs, kw_names):
        try:
            sig = inspect.signature(resolver)
        except (TypeError, ValueError):
            return                       # builtins without introspection
        try:
            sig.bind(*([None] * n_inputs), **dict.fromkeys(kw_names))
        except TypeError as e:
            emit(name, "RP007",
                 f"op '{name}' resolver signature {sig} cannot take its "
                 f"sample inputs ({n_inputs} positional"
                 + (f" + kwargs {sorted(kw_names)}" if kw_names else "")
                 + f"): {e}",
                 "align the sample builder/kwargs with the resolver "
                 "signature")
