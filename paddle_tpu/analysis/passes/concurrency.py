"""concurrency — lock-discipline analysis for the threaded serving fleet.

The serving front door is deeply multithreaded: per-replica step-loop
threads, RPC server threads, membership heartbeats, supervisor respawn
loops, journal pump threads, a ThreadingHTTPServer gateway.  Seventeen
modules hold ``threading.Lock``/``RLock``/``Condition`` objects, and the
bug classes that machinery breeds — a field guarded in one method and
naked in another, blocking I/O under a held lock, a missed ``notify``
ownership rule, two locks taken in opposite orders — are exactly the ones
unit tests miss until a chaos run hangs.  This pass infers each class's
lock discipline from the AST and enforces it:

  * **CC101** guarded-attribute race: an instance attribute written under
    ``with self._lock`` in one method but read/written with no lock held
    in another (``__init__``/``__new__`` exempt — the object is not shared
    yet).  Warning: lock-free reads of monotonic flags are sometimes
    deliberate; such sites carry a pragma saying why they are safe.
  * **CC102** blocking call while holding a lock: ``time.sleep`` (or an
    injectable ``sleep=time.sleep`` attribute), socket
    send/recv/accept/connect, ``os.fsync``, ``subprocess.*``,
    ``Thread.join`` on a thread attribute, and ``retry_call`` — resolved
    one call-hop deep through same-class helper methods, so ``with
    self._mu: self._flush()`` is caught when ``_flush`` fsyncs.  Warning:
    a lock whose express purpose is serializing the blocking channel
    (one-socket RPC clients, fsync-before-ack journals) is deliberate and
    carries a pragma.
  * **CC103** condition misuse: ``cv.wait()`` not inside a ``while`` loop
    re-checking its predicate (spurious wakeups and barging make an
    ``if``-guarded wait a race), or ``notify``/``notify_all`` outside the
    owning ``with cv`` (raises RuntimeError at runtime).  Error.
  * **CC104** lock-order inversion: a per-module acquisition graph (lock
    held while acquiring another → edge) with a cycle — A then B on one
    path, B then A on another — citing both sites.  Error.
  * **CC105** self-deadlock: a non-reentrant ``threading.Lock`` (or a
    ``Condition`` wrapping one) re-acquired along an intra-class call
    chain: ``with self._mu: self._helper()`` where ``_helper`` takes
    ``self._mu`` again.  Error.

Inference is class-scoped (the ISSUE's "which lock guards what" is a
per-object protocol) with two resolution aids shared by the rules: a
method whose every intra-class call site holds lock L is analyzed as if
it held L itself (private helpers documented "caller holds the lock"),
and call sites in ``__init__`` neither grant nor revoke that inheritance.
Module-level locks (``_lock = threading.Lock()`` guarding a global
registry) participate in CC102/CC103/CC104.  Nested ``def``/``lambda``
bodies run later, possibly on another thread, so they never inherit the
lexically-enclosing held set.
"""
from __future__ import annotations

import ast

from ..framework import AnalysisPass, Finding, register_pass
from ..resolve import Imports

_CC101_HINT = ("take the guarding lock around this access, or mark a "
               "deliberately lock-free access (monotonic flag, "
               "snapshot-staleness-tolerant read) with a pragma saying why "
               "it is safe")

_CC102_HINT = ("move the blocking call outside the with block (snapshot "
               "state under the lock, do I/O after); a lock that exists to "
               "serialize the blocking channel carries a pragma saying so")

_CC103_WAIT_HINT = ("wrap the wait in `while not <predicate>:` — spurious "
                    "wakeups and lock barging mean one wakeup does not "
                    "imply the predicate holds")

_CC103_NOTIFY_HINT = ("notify only while holding the condition's lock "
                      "(inside `with cv:`); outside it raises RuntimeError")

_CC104_HINT = ("pick one global order for the two locks and acquire them "
               "in that order on every path (document it where the locks "
               "are constructed)")

_CC105_HINT = ("use threading.RLock when a lock must be re-entered on an "
               "intra-class call chain, or hoist the inner acquisition to "
               "the callers")

# threading constructors, by canonical dotted path (resolve.Imports sees
# through `import threading` / `from threading import Lock` / aliases)
_LOCK_CTORS = {"threading.Lock": False, "threading.RLock": True}
_CONDITION_CTOR = "threading.Condition"
_THREAD_CTOR = "threading.Thread"

# blocking socket operations, matched by method name on any receiver
_SOCK_METHODS = {"sendall", "recv", "recv_into", "accept", "connect"}

# container mutators: a call to one of these on `self.X` writes X's state
_MUTATORS = {"append", "appendleft", "extend", "add", "insert", "remove",
             "discard", "pop", "popleft", "clear", "update", "setdefault"}

_INIT_METHODS = ("__init__", "__new__")


class _Lock:
    """One inferred lock object: a class attribute or module global."""

    def __init__(self, key, display, reentrant, condition):
        self.key = key                # unique per module: "Cls.attr" / name
        self.display = display        # "self._mu" / "_lock"
        self.reentrant = reentrant
        self.condition = condition


def _lock_of_ctor(call, imports):
    """(reentrant, is_condition) when ``call`` constructs a lock, else
    None.  ``Condition()`` defaults to an RLock; ``Condition(Lock())`` is
    non-reentrant; a non-literal lock argument gets the benefit of the
    doubt (reentrant)."""
    canon = imports.canonical(call.func)
    if canon in _LOCK_CTORS:
        return _LOCK_CTORS[canon], False
    if canon == _CONDITION_CTOR:
        reentrant = True
        if call.args and isinstance(call.args[0], ast.Call):
            inner = imports.canonical(call.args[0].func)
            if inner in _LOCK_CTORS:
                reentrant = _LOCK_CTORS[inner]
        return reentrant, True
    return None


def _self_attr(node, selfname):
    """X when ``node`` is ``self.X`` (for this method's self name)."""
    if (selfname and isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == selfname):
        return node.attr
    return None


class _Method:
    def __init__(self, name, node, selfname):
        self.name = name
        self.node = node
        self.selfname = selfname
        # every descendant node -> (frozenset of held lock keys, nested?)
        self.ctx: dict[ast.AST, tuple[frozenset, bool]] = {}
        # lexical acquisitions: (lock key, line, held-before, nested?)
        self.acquisitions: list[tuple] = []
        self.inherited: frozenset = frozenset()

    def held(self, node):
        lex, _ = self.ctx.get(node, (frozenset(), False))
        return lex | self.inherited

    def nested(self, node):
        return self.ctx.get(node, (frozenset(), False))[1]


def _collect(method, class_locks, module_locks):
    """Populate ``method.ctx``/``method.acquisitions`` by walking the body
    with the lexically-held lock set threaded through ``with`` blocks."""
    selfname = method.selfname

    def lock_key(expr):
        attr = _self_attr(expr, selfname)
        if attr is not None and attr in class_locks:
            return class_locks[attr].key
        if isinstance(expr, ast.Name) and expr.id in module_locks:
            return module_locks[expr.id].key
        return None

    def walk(node, held, nested):
        method.ctx[node] = (held, nested)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                walk(item.context_expr, held, nested)
                if item.optional_vars is not None:
                    walk(item.optional_vars, held, nested)
                key = lock_key(item.context_expr)
                if key is not None:
                    method.acquisitions.append(
                        (key, node.lineno, held, nested))
                    held = held | {key}
            for stmt in node.body:
                walk(stmt, held, nested)
            return
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "wait_for"):
            # a cv.wait_for(lambda: ...) predicate is the exception to the
            # nested-lambda rule: the condition re-acquires its lock around
            # every evaluation, so the predicate body runs with it held
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.Lambda):
                    for sub in ast.walk(child):
                        method.ctx[sub] = (held, nested)
                else:
                    walk(child, held, nested)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested def/lambda body runs later, possibly on another
            # thread: it holds nothing, whatever encloses it lexically
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in ast.iter_child_nodes(node):
                if child in body:
                    walk(child, frozenset(), True)
                else:
                    walk(child, held, nested)   # decorators/defaults: now
            return
        for child in ast.iter_child_nodes(node):
            walk(child, held, nested)

    for stmt in method.node.body:
        walk(stmt, frozenset(), False)


def _intra_calls(method, methods):
    """(callee name, call node) for every ``self.m(...)`` where ``m`` is a
    sibling method."""
    out = []
    for node in method.ctx:
        if isinstance(node, ast.Call):
            attr = _self_attr(node.func, method.selfname)
            if attr is not None and attr in methods:
                out.append((attr, node))
    return out


def _infer_inherited(methods, all_keys):
    """Greatest-fixpoint lock inheritance: a method whose every non-init
    intra-class call site holds L is analyzed as holding L ("caller holds
    the lock" helpers).  Methods with no such call sites inherit nothing —
    they are public entry points."""
    sites: dict[str, list] = {m: [] for m in methods}
    for caller in methods.values():
        if caller.name in _INIT_METHODS:
            continue
        for callee, node in _intra_calls(caller, methods):
            lex, nested = caller.ctx[node]
            if not nested:
                sites[callee].append((caller.name, lex))
    for m in methods.values():
        m.inherited = frozenset(all_keys) if sites[m.name] else frozenset()
    for _ in range(len(methods) + 1):
        changed = False
        for m in methods.values():
            if not sites[m.name]:
                continue
            new = frozenset(all_keys)
            for caller_name, lex in sites[m.name]:
                new &= lex | methods[caller_name].inherited
            if new != m.inherited:
                m.inherited = new
                changed = True
        if not changed:
            break
    return sites


def _in_loop(node, parents):
    """Is ``node`` lexically inside a while/for loop of its own def?"""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.While, ast.For, ast.AsyncFor)):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return False
        cur = parents.get(cur)
    return False


def _access_kind(node, parents):
    """'write' / 'read' for a ``self.X`` attribute node: stores, augmented
    assigns, subscript stores (``self.X[k] = v``) and container-mutator
    calls (``self.X.append(v)``) write; everything else reads."""
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return "write"
    parent = parents.get(node)
    if (isinstance(parent, ast.Subscript) and parent.value is node
            and isinstance(parent.ctx, (ast.Store, ast.Del))):
        return "write"
    if (isinstance(parent, ast.Attribute) and parent.value is node
            and parent.attr in _MUTATORS):
        grand = parents.get(parent)
        if isinstance(grand, ast.Call) and grand.func is parent:
            return "write"
    return "read"


def _sleep_attrs(cls_methods, imports):
    """Attributes bound from a parameter whose default is ``time.sleep``
    (the injectable-sleep idiom): calls through them block like
    ``time.sleep`` itself."""
    out = set()
    for m in cls_methods.values():
        args = m.node.args
        named = args.posonlyargs + args.args + args.kwonlyargs
        defaults = ([None] * (len(args.posonlyargs + args.args)
                              - len(args.defaults))
                    + list(args.defaults) + list(args.kw_defaults))
        sleepy = {a.arg for a, d in zip(named, defaults)
                  if d is not None and imports.canonical(d) == "time.sleep"}
        if not sleepy:
            continue
        for node in ast.walk(m.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Name)
                    and node.value.id in sleepy):
                attr = _self_attr(node.targets[0], m.selfname)
                if attr is not None:
                    out.add(attr)
    return out


def _blocking_desc(call, imports, selfname, sleep_attrs, thread_attrs):
    """Human-readable description when ``call`` is a known blocking
    operation, else None."""
    canon = imports.canonical(call.func)
    if canon == "time.sleep":
        return "time.sleep()"
    if canon == "os.fsync":
        return "os.fsync()"
    if canon == "socket.create_connection":
        return "socket.create_connection()"
    if canon and (canon == "subprocess" or canon.startswith("subprocess.")):
        return canon + "()"
    if canon and (canon == "retry_call" or canon.endswith(".retry_call")):
        return "retry_call() (sleeps through its backoff policy)"
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr in _SOCK_METHODS:
            # module-level .connect()/.accept() of an imported non-socket
            # module (sqlite3.connect, ...) is an API call, not socket I/O
            recv = imports.canonical(f.value)
            if not (recv and recv != "socket"
                    and recv in set(imports.aliases.values())):
                return f"socket .{f.attr}()"
        if _self_attr(f, selfname) in sleep_attrs:
            return f"self.{f.attr}() (injectable sleep)"
        if f.attr == "join" and _self_attr(f.value, selfname) in thread_attrs:
            return f"self.{f.value.attr}.join()"
    return None


def _find_cycles(edges):
    """Cycles in the acquisition graph as node tuples, deduped by node
    set.  Graphs here are tiny (a handful of locks per module), so a plain
    DFS per node is fine."""
    adj: dict[str, list[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    cycles, seen = [], set()

    def dfs(start, node, path):
        for nxt in adj.get(node, ()):
            if nxt == start:
                key = frozenset(path)
                if key not in seen:
                    seen.add(key)
                    cycles.append(tuple(path))
            elif nxt not in path and nxt > start:
                # only walk nodes ordered after start: each cycle is
                # discovered exactly once, from its smallest node
                dfs(start, nxt, path + [nxt])

    for start in sorted(adj):
        dfs(start, start, [start])
    return cycles


@register_pass
class ConcurrencyPass(AnalysisPass):
    name = "concurrency"
    version = 1
    codes = ("CC101", "CC102", "CC103", "CC104", "CC105")
    description = ("lock discipline: guarded-attribute races (CC101), "
                   "blocking calls under a held lock (CC102), condition "
                   "wait/notify misuse (CC103), lock-order inversion "
                   "(CC104), non-reentrant self-deadlock (CC105)")

    def check_file(self, src) -> list[Finding]:
        from ..framework import Project
        imports = Imports(src.tree, Project.module_name(src.path))
        parents = {}
        for node in ast.walk(src.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        # module-level locks: NAME = threading.Lock()/RLock()/Condition()
        module_locks: dict[str, _Lock] = {}
        for stmt in src.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)):
                kind = _lock_of_ctor(stmt.value, imports)
                if kind is not None:
                    name = stmt.targets[0].id
                    module_locks[name] = _Lock(name, name, *kind)

        findings: list[Finding] = []
        edges: dict[tuple, tuple] = {}   # (a, b) -> (line, where)
        locks_by_key: dict[str, _Lock] = {l.key: l
                                          for l in module_locks.values()}

        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(src, node, imports, parents, module_locks,
                                  locks_by_key, edges, findings)
        # module-level functions participate in CC102/CC103/CC104
        for stmt in src.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                m = _Method(stmt.name, stmt, None)
                _collect(m, {}, module_locks)
                self._check_blocking(src, m, {}, imports, set(), set(),
                                     locks_by_key, findings)
                self._check_conditions(src, m, {}, module_locks, parents,
                                       locks_by_key, findings)
                for key, line, held, nested in m.acquisitions:
                    if nested:
                        continue
                    for h in held:
                        edges.setdefault((h, key), (line, stmt.name))

        for cyc in _find_cycles(set(edges)):
            cites = []
            for i, a in enumerate(cyc):
                b = cyc[(i + 1) % len(cyc)]
                line, where = edges[(a, b)]
                cites.append((line, where, a, b))
            first = min(cites)
            order = " -> ".join(locks_by_key[k].display for k in cyc)
            sites = "; ".join(
                f"{locks_by_key[a].display} then {locks_by_key[b].display} "
                f"in {where}()" for line, where, a, b in cites)
            findings.append(Finding(
                self.name, "CC104", src.path, first[0],
                f"lock-order inversion: cycle {order} -> "
                f"{locks_by_key[cyc[0]].display} ({sites}) — two threads "
                f"taking these paths concurrently deadlock",
                _CC104_HINT, severity="error"))
        findings.sort(key=lambda f: (f.line, f.code))
        return findings

    # ---- per-class analysis --------------------------------------------------
    def _check_class(self, src, cls, imports, parents, module_locks,
                     locks_by_key, edges, findings):
        defs = [n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        methods: dict[str, _Method] = {}
        class_locks: dict[str, _Lock] = {}
        thread_attrs: set[str] = set()

        # class-body lock attributes: _lock = threading.Lock()
        for stmt in cls.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)):
                kind = _lock_of_ctor(stmt.value, imports)
                if kind is not None:
                    attr = stmt.targets[0].id
                    class_locks[attr] = _Lock(f"{cls.name}.{attr}",
                                              f"self.{attr}", *kind)
        for d in defs:
            deco = {getattr(x, "id", None) for x in d.decorator_list}
            args = d.args.posonlyargs + d.args.args
            selfname = (args[0].arg if args and "staticmethod" not in deco
                        else None)
            methods[d.name] = _Method(d.name, d, selfname)
            for node in ast.walk(d):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.value, ast.Call)):
                    attr = _self_attr(node.targets[0], selfname)
                    if attr is None:
                        continue
                    kind = _lock_of_ctor(node.value, imports)
                    if kind is not None:
                        class_locks[attr] = _Lock(f"{cls.name}.{attr}",
                                                  f"self.{attr}", *kind)
                    elif imports.canonical(node.value.func) == _THREAD_CTOR:
                        thread_attrs.add(attr)
        if not class_locks:
            return
        locks_by_key.update({l.key: l for l in class_locks.values()})
        class_keys = {l.key for l in class_locks.values()}
        for m in methods.values():
            _collect(m, class_locks, module_locks)
        _infer_inherited(methods, class_keys)
        sleep_attrs = _sleep_attrs(methods, imports)

        self._check_guarded_attrs(src, cls, methods, class_locks, class_keys,
                                  parents, locks_by_key, findings)
        for m in methods.values():
            self._check_blocking(src, m, methods, imports, sleep_attrs,
                                 thread_attrs, locks_by_key, findings)
            self._check_conditions(src, m, class_locks, module_locks,
                                   parents, locks_by_key, findings)
        self._check_self_deadlock(src, cls, methods, class_locks,
                                  locks_by_key, findings)
        for m in methods.values():
            for key, line, held, nested in m.acquisitions:
                if nested:
                    continue
                for h in held | (m.inherited - {key}):
                    if h != key:
                        edges.setdefault((h, key), (line, m.name))
            # one hop: holding L while calling a sibling that acquires K
            for callee, node in _intra_calls(m, methods):
                held = m.held(node)
                if not held or m.nested(node):
                    continue
                for key, line, _, nested in methods[callee].acquisitions:
                    if nested:
                        continue
                    for h in held:
                        if h != key:
                            edges.setdefault((h, key), (node.lineno, m.name))

    # ---- CC101 ---------------------------------------------------------------
    def _check_guarded_attrs(self, src, cls, methods, class_locks,
                             class_keys, parents, locks_by_key, findings):
        guarded: dict[str, set] = {}     # attr -> guarding lock keys
        accesses = []                    # (attr, method, kind, line, locked)
        for m in methods.values():
            if m.name in _INIT_METHODS or m.selfname is None:
                continue
            for node in m.ctx:
                attr = _self_attr(node, m.selfname)
                if attr is None or attr in class_locks:
                    continue
                kind = _access_kind(node, parents)
                locked = m.held(node) & class_keys
                if kind == "write" and locked:
                    guarded.setdefault(attr, set()).update(locked)
                accesses.append((attr, m.name, kind, node.lineno,
                                 bool(locked)))
        reported = set()
        for attr, mname, kind, line, locked in sorted(
                accesses, key=lambda a: a[3]):
            if locked or attr not in guarded or (attr, mname) in reported:
                continue
            reported.add((attr, mname))
            guards = ", ".join(sorted(locks_by_key[k].display
                                      for k in guarded[attr]))
            verb = "written" if kind == "write" else "read"
            findings.append(Finding(
                self.name, "CC101", src.path, line,
                f"{cls.name}.{attr} is written under {guards} elsewhere "
                f"but {verb} with no lock held in {mname}()",
                _CC101_HINT, severity="warning"))

    # ---- CC102 ---------------------------------------------------------------
    def _check_blocking(self, src, m, methods, imports, sleep_attrs,
                        thread_attrs, locks_by_key, findings):
        def direct_sites(method):
            out = []
            for node in method.ctx:
                if isinstance(node, ast.Call) and not method.nested(node):
                    desc = _blocking_desc(node, imports, method.selfname,
                                          sleep_attrs, thread_attrs)
                    if desc is not None:
                        out.append(desc)
            return out

        for node in m.ctx:
            if not isinstance(node, ast.Call) or m.nested(node):
                continue
            held, _ = m.ctx[node]          # lexical only: helpers called
            if not held:                   # under a lock are flagged at
                continue                   # their call site, one hop deep
            locks = ", ".join(sorted(locks_by_key[k].display for k in held))
            desc = _blocking_desc(node, imports, m.selfname, sleep_attrs,
                                  thread_attrs)
            callee = _self_attr(node.func, m.selfname)
            if desc is None and callee in methods and callee != m.name:
                inner = direct_sites(methods[callee])
                if inner:
                    desc = f"self.{callee}() which does {inner[0]}"
            if desc is not None:
                findings.append(Finding(
                    self.name, "CC102", src.path, node.lineno,
                    f"blocking {desc} while holding {locks} in {m.name}() "
                    f"— every thread contending on the lock stalls behind "
                    f"this call",
                    _CC102_HINT, severity="warning"))

    # ---- CC103 ---------------------------------------------------------------
    def _check_conditions(self, src, m, class_locks, module_locks, parents,
                          locks_by_key, findings):
        conds = {l.key: l for l in class_locks.values() if l.condition}
        conds.update({l.key: l for l in module_locks.values()
                      if l.condition})

        def cond_key(expr):
            attr = _self_attr(expr, m.selfname)
            if attr is not None and attr in class_locks \
                    and class_locks[attr].condition:
                return class_locks[attr].key
            if (isinstance(expr, ast.Name) and expr.id in module_locks
                    and module_locks[expr.id].condition):
                return module_locks[expr.id].key
            return None

        for node in m.ctx:
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            key = cond_key(node.func.value)
            if key is None:
                continue
            disp = locks_by_key[key].display
            if node.func.attr == "wait" and not _in_loop(node, parents):
                findings.append(Finding(
                    self.name, "CC103", src.path, node.lineno,
                    f"{disp}.wait() in {m.name}() is not inside a while "
                    f"loop re-checking its predicate — spurious wakeups "
                    f"and lock barging make a single wakeup meaningless",
                    _CC103_WAIT_HINT, severity="error"))
            elif node.func.attr in ("notify", "notify_all") \
                    and key not in m.held(node):
                findings.append(Finding(
                    self.name, "CC103", src.path, node.lineno,
                    f"{disp}.{node.func.attr}() in {m.name}() outside "
                    f"`with {disp}:` — notifying without owning the "
                    f"condition's lock raises RuntimeError",
                    _CC103_NOTIFY_HINT, severity="error"))

    # ---- CC105 ---------------------------------------------------------------
    def _check_self_deadlock(self, src, cls, methods, class_locks,
                             locks_by_key, findings):
        nonreentrant = {l.key for l in class_locks.values()
                        if not l.reentrant}
        if not nonreentrant:
            return
        acq: dict[str, frozenset] = {
            name: frozenset(k for k, _, _, nested in m.acquisitions
                            if not nested)
            for name, m in methods.items()}
        for _ in range(len(methods) + 1):     # transitive closure
            changed = False
            for m in methods.values():
                new = acq[m.name]
                for callee, node in _intra_calls(m, methods):
                    if not m.nested(node):
                        new = new | acq[callee]
                if new != acq[m.name]:
                    acq[m.name] = new
                    changed = True
            if not changed:
                break
        for m in methods.values():
            for key, line, held, nested in m.acquisitions:
                if not nested and key in held and key in nonreentrant:
                    findings.append(Finding(
                        self.name, "CC105", src.path, line,
                        f"non-reentrant {locks_by_key[key].display} "
                        f"re-acquired in a nested with in {m.name}() — "
                        f"deadlocks immediately",
                        _CC105_HINT, severity="error"))
            for callee, node in _intra_calls(m, methods):
                if m.nested(node):
                    continue
                again = m.held(node) & nonreentrant & acq[callee]
                for key in sorted(again):
                    findings.append(Finding(
                        self.name, "CC105", src.path, node.lineno,
                        f"self-deadlock: {m.name}() holds non-reentrant "
                        f"{locks_by_key[key].display} and calls "
                        f"self.{callee}(), which acquires it again",
                        _CC105_HINT, severity="error"))
