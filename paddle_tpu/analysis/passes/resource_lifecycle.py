"""resource-lifecycle — acquire/release discipline on exception paths.

The runtime audits resources dynamically (``PagePool.audit_refcounts``,
lease heartbeats); these rules are the static counterpart, catching the
paths a chaos run only hits when the fault lands exactly between an
acquire and its release:

* **RL101** — a socket / file / thread acquired into a local or
  ``self.*`` name, followed by calls that can raise before any
  ``close()``/``join()`` is guaranteed by a ``with``, ``try/finally`` or
  an ``except`` that releases it.  A constructor (`__init__`) that raises
  after acquiring leaks unconditionally: the caller never gets an object
  to close.
* **RL102** — a ``PagePool`` ``alloc_page``/``ref_page`` whose matching
  ``unref_page``/``free`` is separated from it by calls that can raise,
  with no ``except``/``finally`` rollback in between — the static shadow
  of ``audit_refcounts``.
* **RL103** — a class that registers a membership lease
  (``self.lease = membership.register(...)``) but whose shutdown methods
  (``close``/``stop``/``drain``/...) never reach a ``release()``/
  ``evict()``: the lease survives the owner and routes traffic at a
  corpse until TTL expiry.

Scope: production code and lint fixtures; files under ``tests/`` (except
``graftlint_fixtures``) are skipped — tests hold resources deliberately
and die with the process.
"""
from __future__ import annotations

import ast

from ..framework import AnalysisPass, Finding, norm_path, register_pass
from ..resolve import Imports

_ACQUIRE_KINDS = (
    ("socket.socket", "socket"),
    ("socket.create_connection", "socket"),
    ("socket.socketpair", "socket"),
    ("threading.Thread", "thread"),
)
_RELEASE = {"file": ("close",), "socket": ("close",), "thread": ("join",)}
_POOL_ACQ = ("alloc_page", "ref_page")
_POOL_REL = ("unref_page", "free_page", "release_page", "free")
_SHUTDOWN_NAMES = ("close", "stop", "shutdown", "drain", "release",
                   "terminate", "__exit__")

_HINTS = {
    "RL101": "wrap the risky calls in try/except that closes the resource "
             "(or use `with`); a constructor that raises after acquiring "
             "leaks the resource unconditionally",
    "RL102": "move the page ops into a try whose except/finally rolls the "
             "ref back (unref_page/free), or reorder so nothing can raise "
             "between them",
    "RL103": "release or evict the lease from the owner's close()/stop() "
             "path so membership sees `leave` instead of a TTL expiry",
}

_DOCS = {
    "RL101": "Acquire-without-guaranteed-release: a socket/file/thread "
             "bound to a name, then calls that can raise before any close "
             "is guaranteed.  On the exception path the resource leaks — "
             "fd exhaustion under retry loops, EADDRINUSE on respawn.",
    "RL102": "PagePool ref/alloc without a guarded rollback: if a call "
             "raises between alloc_page/ref_page and its unref, the page's "
             "refcount is permanently high and audit_refcounts only finds "
             "it after the capacity is already gone.",
    "RL103": "Lease registered with no release reachable from shutdown: "
             "the membership plane keeps routing to the dead owner until "
             "TTL expiry instead of seeing a clean `leave`.",
}


def _terminal_name(expr):
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_poolish(expr):
    name = _terminal_name(expr)
    return name is not None and "pool" in name.lower().lstrip("_")


def _call_desc(call):
    """Short stable spelling of a call's target for messages."""
    parts = []
    f = call.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts)) or "<call>"


def _own_nodes(func):
    """All nodes of ``func`` excluding nested function/lambda bodies."""
    out = []
    stack = list(ast.iter_child_nodes(func))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _same_target(expr, target):
    """``expr`` spells the same Name / self.attr as ``target``."""
    if isinstance(target, ast.Name):
        return isinstance(expr, ast.Name) and expr.id == target.id
    if isinstance(target, ast.Attribute):
        return (isinstance(expr, ast.Attribute)
                and expr.attr == target.attr
                and isinstance(expr.value, ast.Name)
                and isinstance(target.value, ast.Name)
                and expr.value.id == target.value.id)
    return False


def _contains_target(node, target):
    return any(_same_target(n, target) for n in ast.walk(node))


class _FuncCtx:
    """Parent links and try-guard queries within one function."""

    def __init__(self, func):
        self.func = func
        self.nodes = _own_nodes(func)
        self.parent: dict = {}
        stack = [func]
        while stack:
            n = stack.pop()
            for c in ast.iter_child_nodes(n):
                self.parent[c] = n
                stack.append(c)

    def ancestors(self, node):
        while node in self.parent:
            node = self.parent[node]
            yield node

    def in_handler_of_try_containing(self, node, other):
        """``node`` sits in an except-handler/orelse of a Try whose body
        contains ``other`` (i.e. runs only when ``other``'s region threw)."""
        for anc in self.ancestors(node):
            if isinstance(anc, ast.Try):
                in_body = any(other is n or other in ast.walk(s)
                              for s in anc.body for n in ast.walk(s))
                if in_body:
                    in_rescue = any(
                        node in ast.walk(h)
                        for h in list(anc.handlers) + list(anc.orelse))
                    if in_rescue:
                        return True
        return False

    def guarded_by_release(self, node, release_pred):
        """Some ancestor Try holds ``node`` in its body and releases the
        resource in an except-handler or finally block."""
        child = node
        for anc in self.ancestors(node):
            if isinstance(anc, ast.Try):
                in_body = any(child is s or any(child is n for n in
                                                ast.walk(s))
                              for s in anc.body)
                if in_body:
                    rescue = list(anc.finalbody)
                    for h in anc.handlers:
                        rescue.extend(h.body)
                    for s in rescue:
                        for n in ast.walk(s):
                            if isinstance(n, ast.Call) and release_pred(n):
                                return True
            child = anc
        return False


@register_pass
class ResourceLifecyclePass(AnalysisPass):
    name = "resource_lifecycle"
    version = 1
    codes = ("RL101", "RL102", "RL103")
    rule_docs = _DOCS
    rule_severities = {"RL101": "warning", "RL102": "warning",
                       "RL103": "warning"}
    description = ("socket/file/thread leaks on exception paths, unguarded "
                   "PagePool ref/alloc, leases with no shutdown release")

    def check_file(self, src) -> list[Finding]:
        rel = norm_path(src.path)
        if rel.startswith("tests/") and "graftlint_fixtures" not in rel:
            return []
        imports = Imports(src.tree, None)
        findings: list[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ctx = _FuncCtx(node)
                self._rl101(src, imports, ctx, findings)
                self._rl102(src, ctx, findings)
            elif isinstance(node, ast.ClassDef):
                self._rl103(src, node, findings)
        return findings

    # ---- RL101: acquire without guaranteed release ---------------------------
    def _acquire_kind(self, imports, call):
        canon = imports.canonical(call.func)
        if canon == "open":
            return "file"
        for key, kind in _ACQUIRE_KINDS:
            if canon == key or (canon and canon.endswith("." + key)):
                if kind == "thread" and any(
                        k.arg == "daemon" and isinstance(k.value, ast.Constant)
                        and k.value.value for k in call.keywords):
                    return None            # daemon thread: fire-and-forget
                return kind
        return None

    def _rl101(self, src, imports, ctx, findings):
        func = ctx.func
        for stmt in ctx.nodes:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.value, ast.Call)):
                continue
            kind = self._acquire_kind(imports, stmt.value)
            if kind is None:
                continue
            target = stmt.targets[0]
            if isinstance(target, ast.Name):
                self._rl101_check(src, ctx, stmt, target, kind, findings,
                                  ctor=False)
            elif (isinstance(target, ast.Attribute)
                  and isinstance(target.value, ast.Name)
                  and target.value.id == "self"
                  and func.name == "__init__"):
                self._rl101_check(src, ctx, stmt, target, kind, findings,
                                  ctor=True)

    def _rl101_check(self, src, ctx, acq_stmt, target, kind, findings, ctor):
        release_names = _RELEASE[kind]
        acq_call = acq_stmt.value

        def is_release(call):
            return (isinstance(call.func, ast.Attribute)
                    and call.func.attr in release_names
                    and _same_target(call.func.value, target))

        releases, escapes, managed = [], [], False
        for n in ctx.nodes:
            if isinstance(n, ast.withitem) and \
                    _contains_target(n.context_expr, target):
                managed = True
            elif isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)):
                if n.value is not None and _contains_target(n.value, target):
                    escapes.append(n.lineno)
            elif isinstance(n, ast.Call):
                if is_release(n):
                    releases.append(n)
                elif any(_contains_target(a, target)
                         for a in list(n.args) + [k.value
                                                  for k in n.keywords]):
                    escapes.append(n.lineno)
            elif isinstance(n, ast.Assign) and n is not acq_stmt and \
                    _contains_target(n.value, target):
                escapes.append(n.lineno)       # aliased or stored
        if managed:
            return
        straight_rel = [r.lineno for r in releases
                        if not any(isinstance(a, ast.ExceptHandler)
                                   for a in ctx.ancestors(r))
                        and not self._in_finalbody(ctx, r)]
        boundary = min(straight_rel + escapes + [float("inf")])
        risky = []
        for n in ctx.nodes:
            if not isinstance(n, ast.Call) or n is acq_call:
                continue
            if not (acq_stmt.lineno < n.lineno < boundary):
                continue
            if is_release(n):
                continue
            if kind == "thread" and isinstance(n.func, ast.Attribute) and \
                    _same_target(n.func.value, target):
                continue                       # t.start() before join is fine
            if ctx.in_handler_of_try_containing(n, acq_call):
                continue                       # runs only if acquire threw
            risky.append(n)
        unprotected = [n for n in risky
                       if not ctx.guarded_by_release(n, is_release)]
        desc = _terminal_name(target) or "resource"
        if unprotected:
            first = min(unprotected, key=lambda n: n.lineno)
            where = ("constructor raises after acquiring — the caller "
                     "never gets an object to close" if ctor else
                     "no try/finally or closing except guards it")
            findings.append(Finding(
                self.name, "RL101", src.path, acq_stmt.lineno,
                f"{kind} {desc!r} can leak: {_call_desc(first)}(...) may "
                f"raise before {release_names[0]}() is guaranteed ({where})",
                _HINTS["RL101"], severity="warning"))
        elif not ctor and not releases and not escapes:
            findings.append(Finding(
                self.name, "RL101", src.path, acq_stmt.lineno,
                f"{kind} {desc!r} is never released on any path "
                f"(no {release_names[0]}(), with-block, or handoff)",
                _HINTS["RL101"], severity="warning"))

    @staticmethod
    def _in_finalbody(ctx, node):
        child = node
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.Try) and any(
                    child is s or any(child is n for n in ast.walk(s))
                    for s in anc.finalbody):
                return True
            child = anc
        return False

    # ---- RL102: PagePool ref/alloc without guarded rollback ------------------
    def _rl102(self, src, ctx, findings):
        def is_pool_release(call):
            return (isinstance(call.func, ast.Attribute)
                    and call.func.attr in _POOL_REL
                    and _is_poolish(call.func.value))

        for site in ctx.nodes:
            if not (isinstance(site, ast.Call)
                    and isinstance(site.func, ast.Attribute)
                    and site.func.attr in _POOL_ACQ
                    and _is_poolish(site.func.value)):
                continue
            parent = ctx.parent.get(site)
            if isinstance(parent, ast.Return):
                continue                       # caller owns the ref
            if ctx.guarded_by_release(site, is_pool_release):
                continue
            rel_after = [n.lineno for n in ctx.nodes
                         if isinstance(n, ast.Call) and is_pool_release(n)
                         and n.lineno > site.lineno]
            boundary = min(rel_after + [float("inf")])
            risky = [n for n in ctx.nodes
                     if isinstance(n, ast.Call)
                     and site.lineno < n.lineno < boundary
                     and not (isinstance(n.func, ast.Attribute)
                              and _is_poolish(n.func.value))
                     and not ctx.guarded_by_release(n, is_pool_release)]
            if risky:
                first = min(risky, key=lambda n: n.lineno)
                findings.append(Finding(
                    self.name, "RL102", src.path, site.lineno,
                    f"{site.func.attr}() ref can strand: "
                    f"{_call_desc(first)}(...) may raise before the "
                    "matching unref/free reaches an except/finally",
                    _HINTS["RL102"], severity="warning"))

    # ---- RL103: lease with no shutdown release -------------------------------
    def _rl103(self, src, cls, findings):
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        leases = []                            # (attr_name, line)
        for m in methods.values():
            for n in _own_nodes(m):
                if (isinstance(n, ast.Assign) and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Attribute)
                        and isinstance(n.targets[0].value, ast.Name)
                        and n.targets[0].value.id == "self"
                        and isinstance(n.value, ast.Call)
                        and isinstance(n.value.func, ast.Attribute)
                        and n.value.func.attr == "register"):
                    recv = (_terminal_name(n.value.func.value) or "").lower()
                    if "member" in recv or "lease" in recv:
                        leases.append((n.targets[0].attr, n.lineno))
        if not leases:
            return
        shutdown = [m for name, m in methods.items()
                    if name in _SHUTDOWN_NAMES]
        # intra-class closure from the shutdown methods
        reachable, frontier = set(), [m.name for m in shutdown]
        while frontier:
            name = frontier.pop()
            if name in reachable or name not in methods:
                continue
            reachable.add(name)
            for n in _own_nodes(methods[name]):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and isinstance(n.func.value, ast.Name)
                        and n.func.value.id == "self"):
                    frontier.append(n.func.attr)
        for attr, line in leases:
            released = False
            for name in reachable:
                for n in _own_nodes(methods[name]):
                    if not (isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Attribute)):
                        continue
                    if n.func.attr in ("release", "evict", "stop_heartbeat"):
                        recv = n.func.value
                        if _same_target(recv, ast.Attribute(
                                value=ast.Name(id="self"), attr=attr)) or \
                                n.func.attr == "evict":
                            released = True
            if not released:
                why = ("no release()/evict() reachable from its shutdown "
                       "methods" if shutdown else
                       "the class has no shutdown method at all")
                findings.append(Finding(
                    self.name, "RL103", src.path, line,
                    f"membership lease 'self.{attr}' is registered but "
                    f"{why} — the fleet sees a TTL expiry, not a clean "
                    "leave", _HINTS["RL103"], severity="warning"))
