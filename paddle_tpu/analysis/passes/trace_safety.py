"""trace-safety — flag JAX trace-unsafe idioms in code reachable from
``jit.to_static`` / ``jax.jit`` / ``scan_steps`` entry points.

The bug class: python that runs *at trace time* but looks like it runs per
call.  A data-dependent ``if`` on a traced tensor either raises
``TracerBoolConversionError`` or (via a value guard) silently recompiles per
branch; ``float()``/``.numpy()`` escapes force a device sync or bake a stale
constant into the trace; ``np.*`` on a tracer concretizes it; writes to
globals fire once at trace time and never again.  None of these are visible
to unit tests that happen to take one branch — exactly the class the
north-star "fast as the hardware allows" goal cannot afford in production.

Mechanics: per file, build a function table, seed a taint set from each jit
entry's non-static parameters, propagate through local assignments and
intra-file calls (including ``self.method`` and bare-name references such as
``jax.lax.scan(body, ...)``) to a fixpoint, then sweep reachable functions
for the four violation shapes.  Static contexts never taint or trigger:
``x is None``, ``isinstance``/``len``/``hasattr``, and shape/dtype metadata
attributes — those are host-known under jit.
"""
from __future__ import annotations

import ast

from ..framework import AnalysisPass, Finding, register_pass
from ._jit import (FunctionTable, collect_jit_sites, dotted, param_names,
                   traced_params)

# attribute reads that are static under jit (shape metadata, framework flags)
_META_ATTRS = {"shape", "ndim", "dtype", "size", "device", "name",
               "stop_gradient", "persistable", "itemsize"}
# builtins whose result is host-static even on traced args
_STATIC_FUNCS = {"len", "isinstance", "hasattr", "getattr", "type", "id",
                 "repr", "str", "format", "print", "issubclass"}
# host-escape method calls on a traced value
_ESCAPE_METHODS = {"numpy", "item", "tolist"}
_ESCAPE_BUILTINS = {"bool", "int", "float"}

_HINTS = {
    "TS101": "use jnp.where/lax.cond, or hoist the branch out of the traced "
             "function (declare the arg static if it is host metadata)",
    "TS102": "keep the value on-device (array compare / jnp op) or move the "
             "read outside the jitted region",
    "TS103": "host materialization breaks the trace; return the tensor and "
             "read it after the step",
    "TS104": "use the jax.numpy equivalent so the op stays in the trace",
    "TS105": "trace-time side effect: it will NOT re-run per call once "
             "compiled; thread the value through returns or framework state",
}


def _is_static_compare(node) -> bool:
    return (isinstance(node, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops))


def _scan(node, tainted, uses, *, taint_mode):
    """Collect tainted-name usages in ``node``.

    taint_mode=False (branch/arg checks): attribute reads on a tainted name
    are allowed (host attributes), method calls are not.
    taint_mode=True (assignment RHS): attribute access propagates taint.
    """
    if node is None or _is_static_compare(node):
        return
    if isinstance(node, ast.Name):
        if node.id in tainted:
            uses.append(node)
        return
    if isinstance(node, ast.Attribute):
        if node.attr in _META_ATTRS:
            return
        if taint_mode:
            _scan(node.value, tainted, uses, taint_mode=taint_mode)
        return
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in _STATIC_FUNCS:
            return
        if isinstance(f, ast.Attribute):
            if f.attr in _META_ATTRS:
                return
            # method call on a traced receiver is a traced use
            _scan(f.value, tainted, uses, taint_mode=True)
        else:
            _scan(f, tainted, uses, taint_mode=taint_mode)
        for a in node.args:
            _scan(a, tainted, uses, taint_mode=taint_mode)
        for kw in node.keywords:
            _scan(kw.value, tainted, uses, taint_mode=taint_mode)
        return
    for child in ast.iter_child_nodes(node):
        _scan(child, tainted, uses, taint_mode=taint_mode)


def _is_tainted(expr, tainted) -> bool:
    uses: list = []
    _scan(expr, tainted, uses, taint_mode=True)
    return bool(uses)


def _target_names(target):
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for e in target.elts:
            out.extend(_target_names(e))
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return []


class _FuncModel:
    """One propagation step over a function body: given tainted params,
    compute tainted locals and the tainted-arg call edges."""

    def __init__(self, fn, table: FunctionTable):
        self.fn = fn
        self.table = table

    def propagate(self, tainted: set) -> tuple[set, list]:
        """Returns (final tainted names, [(callee_name, tainted_param_names
        or None-for-all)])."""
        tainted = set(tainted)
        edges = []
        body = self.fn.body
        for _ in range(2):                     # handle use-before-def loops
            before = len(tainted)
            for stmt in body:
                self._stmt(stmt, tainted)
            if len(tainted) == before:
                break
        for node in ast.walk(self.fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not self.fn:
                continue                       # nested defs analyzed on ref
            if isinstance(node, ast.Call):
                edge = self._call_edge(node, tainted)
                if edge:
                    edges.append(edge)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in self.table.defs and node.id not in tainted:
                    edges.append((node.id, None))   # bare ref: e.g. scan body
        return tainted, edges

    def _stmt(self, stmt, tainted):
        if isinstance(stmt, ast.Assign):
            if _is_tainted(stmt.value, tainted):
                for t in stmt.targets:
                    tainted.update(_target_names(t))
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            if stmt.value is not None and _is_tainted(stmt.value, tainted):
                tainted.update(_target_names(stmt.target))
        elif isinstance(stmt, ast.For):
            self._pair(stmt.target, stmt.iter, tainted, unwrap=True)
            for s in stmt.body + stmt.orelse:
                self._stmt(s, tainted)
        elif isinstance(stmt, (ast.If, ast.While)):
            for s in stmt.body + stmt.orelse:
                self._stmt(s, tainted)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None \
                        and _is_tainted(item.context_expr, tainted):
                    tainted.update(_target_names(item.optional_vars))
            for s in stmt.body:
                self._stmt(s, tainted)
        elif isinstance(stmt, ast.Try):
            for s in stmt.body + stmt.orelse + stmt.finalbody:
                self._stmt(s, tainted)
            for h in stmt.handlers:
                for s in h.body:
                    self._stmt(s, tainted)

    def _pair(self, target, expr, tainted, unwrap=False):
        """Precise taint for ``for a, b in zip(X, Y)`` / ``enumerate(X)``
        loop targets: each name is tainted only by its own source, so a
        static mask zipped against traced values stays untainted."""
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name):
            args = expr.args
            if expr.func.id == "enumerate" and args and unwrap \
                    and isinstance(target, (ast.Tuple, ast.List)) \
                    and len(target.elts) == 2:
                self._pair(target.elts[1], args[0], tainted)
                return
            if expr.func.id == "zip" \
                    and isinstance(target, (ast.Tuple, ast.List)) \
                    and len(target.elts) == len(args) \
                    and not any(isinstance(a, ast.Starred) for a in args):
                for t, a in zip(target.elts, args):
                    self._pair(t, a, tainted)
                return
        if _is_tainted(expr, tainted):
            tainted.update(_target_names(target))

    def _call_edge(self, call, tainted):
        f = call.func
        callee = None
        offset = 0
        if isinstance(f, ast.Name) and f.id in self.table.defs:
            callee = f.id
        elif (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
              and f.value.id in ("self", "cls") and f.attr in self.table.defs
              and self.table.parent_class.get(
                  id(self.table.defs[f.attr])) is not None):
            callee = f.attr
            offset = 1                          # skip the self param
        if callee is None:
            return None
        params = param_names(self.table.defs[callee])[offset:]
        hit = set()
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                if _is_tainted(a.value, tainted):
                    hit.update(params[i:])
                continue
            if i < len(params) and _is_tainted(a, tainted):
                hit.add(params[i])
        for kw in call.keywords:
            if kw.arg is not None and _is_tainted(kw.value, tainted):
                hit.add(kw.arg)
        return (callee, hit) if hit else None


@register_pass
class TraceSafetyPass(AnalysisPass):
    name = "trace-safety"
    version = 1
    codes = ("TS101", "TS102", "TS103", "TS104", "TS105")
    description = ("data-dependent branching, host escapes, np.* calls and "
                   "global mutation inside jit-traced code")

    def check_file(self, src) -> list[Finding]:
        table = FunctionTable()
        table.visit(src.tree)
        sites = collect_jit_sites(src.tree, table)
        if not sites:
            return []
        # ---- taint fixpoint across the intra-file call graph -------------
        taints: dict[str, set] = {}
        work = []
        for s in sites:
            fn = table.defs.get(s.func_name or "")
            if fn is None:
                continue
            t = traced_params(fn, s)
            if taints.get(fn.name, set()) != t:
                taints[fn.name] = taints.get(fn.name, set()) | t
                work.append(fn.name)
        models = {n: _FuncModel(f, table) for n, f in table.defs.items()}
        reachable = set(taints)
        for _ in range(200):                   # fixpoint with a hard bound
            if not work:
                break
            name = work.pop()
            _, edges = models[name].propagate(taints.get(name, set()))
            for callee, hit in edges:
                if hit is None:                # bare reference: all traced
                    hit = set(param_names(table.defs[callee])) - {"self", "cls"}
                cur = taints.get(callee, set())
                if callee not in reachable or not hit <= cur:
                    taints[callee] = cur | hit
                    reachable.add(callee)
                    work.append(callee)
        # ---- findings sweep over reachable functions ---------------------
        findings: list[Finding] = []
        seen = set()

        def emit(node, code, msg):
            key = (node.lineno, code)
            if key not in seen:
                seen.add(key)
                findings.append(Finding(self.name, code, src.path,
                                        node.lineno, msg, _HINTS[code]))

        for name in sorted(reachable):
            fn = table.defs[name]
            tainted, _ = models[name].propagate(taints.get(name, set()))
            self._sweep(fn, tainted, emit)
        return findings

    def _sweep(self, fn, tainted, emit):
        globs = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                globs.update(node.names)
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue                       # nested defs swept separately
            if isinstance(node, (ast.If, ast.While)):
                uses: list = []
                _scan(node.test, tainted, uses, taint_mode=False)
                if uses:
                    kind = "while" if isinstance(node, ast.While) else "if"
                    emit(node, "TS101",
                         f"data-dependent `{kind}` on traced value "
                         f"'{uses[0].id}' — concretizes the tracer or "
                         "recompiles per branch value")
            elif isinstance(node, ast.Call):
                self._sweep_call(node, tainted, emit)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for n in _target_names(t):
                        if n in globs:
                            emit(node, "TS105",
                                 f"write to global/nonlocal '{n}' inside "
                                 "traced code")

    def _sweep_call(self, node, tainted, emit):
        f = node.func
        if isinstance(f, ast.Name) and f.id in _ESCAPE_BUILTINS:
            if any(_tainted_use(a, tainted) for a in node.args):
                emit(node, "TS102",
                     f"`{f.id}()` on a traced value — forces concretization")
            return
        if isinstance(f, ast.Attribute) and f.attr in _ESCAPE_METHODS:
            if _tainted_use(f.value, tainted):
                emit(node, "TS103",
                     f"`.{f.attr}()` on a traced value — host round trip "
                     "inside the trace")
            return
        d = dotted(f)
        if d and (d.startswith("np.") or d.startswith("numpy.")):
            if any(_tainted_use(a, tainted) for a in node.args) or any(
                    _tainted_use(kw.value, tainted) for kw in node.keywords):
                emit(node, "TS104",
                     f"`{d}()` called on a traced value — numpy "
                     "concretizes tracers")


def _tainted_use(expr, tainted) -> bool:
    uses: list = []
    _scan(expr, tainted, uses, taint_mode=False)
    return bool(uses)
