"""Shared helpers for the jit-aware passes: finding functions that enter a
trace (``jit.to_static`` / ``jax.jit`` / ``scan_steps``), their static
arguments, and the per-file function table used for reachability."""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

# dotted-name suffixes that mark a trace entry point
_JIT_CALLS = ("jax.jit", "jit.to_static", "paddle.jit.to_static",
              "paddle_tpu.jit.to_static")
_JIT_BARE = ("to_static", "scan_steps", "pjit")


def dotted(node) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_jit_name(node) -> bool:
    d = dotted(node)
    if d is None:
        return False
    last = d.rsplit(".", 1)[-1]
    return d in _JIT_CALLS or d.endswith(".scan_steps") or last in _JIT_BARE


def _literal_strs(node):
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    return []


def _literal_ints(node):
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    return []


@dataclass
class JitSite:
    """One jit entry: the wrapped function name (if resolvable) and the
    declared static arguments."""
    func_name: str | None
    node: ast.AST
    static_names: set = field(default_factory=set)
    static_nums: set = field(default_factory=set)


def _statics_from_call(call: ast.Call):
    names, nums = set(), set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names |= set(_literal_strs(kw.value))
        elif kw.arg == "static_argnums":
            nums |= set(_literal_ints(kw.value))
    return names, nums


def jit_decorator_info(deco):
    """(static_names, static_nums) if ``deco`` marks a jit entry, else None.

    Recognizes ``@jax.jit``, ``@to_static``, ``@scan_steps``, the called
    forms with kwargs, and ``@functools.partial(jax.jit, static_*=...)``."""
    if is_jit_name(deco):
        return set(), set()
    if isinstance(deco, ast.Call):
        d = dotted(deco.func)
        if d and d.rsplit(".", 1)[-1] == "partial" and deco.args \
                and is_jit_name(deco.args[0]):
            return _statics_from_call(deco)
        if is_jit_name(deco.func):
            return _statics_from_call(deco)
    return None


class FunctionTable(ast.NodeVisitor):
    """All function/method defs in a module, keyed by bare name (last def
    wins) — a deliberate approximation that is robust for the intra-file
    reachability walk these passes need."""

    def __init__(self):
        self.defs: dict[str, ast.AST] = {}
        self.parent_class: dict[int, str | None] = {}
        self._class: list[str] = []

    def visit_ClassDef(self, node):
        self._class.append(node.name)
        self.generic_visit(node)
        self._class.pop()

    def _def(self, node):
        self.defs[node.name] = node
        self.parent_class[id(node)] = self._class[-1] if self._class else None
        self.generic_visit(node)

    visit_FunctionDef = _def
    visit_AsyncFunctionDef = _def


def collect_jit_sites(tree, table: FunctionTable) -> list[JitSite]:
    """Every jit entry in the module: decorated defs plus call-site wraps
    like ``jax.jit(fn, ...)`` / ``to_static(fn)`` where ``fn`` is a local
    function name."""
    sites = []
    for fn in table.defs.values():
        for deco in fn.decorator_list:
            info = jit_decorator_info(deco)
            if info is not None:
                sites.append(JitSite(fn.name, fn, *info))
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and is_jit_name(node.func)
                and node.args):
            continue
        target = node.args[0]
        fname = None
        if isinstance(target, ast.Name) and target.id in table.defs:
            fname = target.id
        elif (isinstance(target, ast.Attribute)
              and isinstance(target.value, ast.Name)
              and target.value.id in ("self", "cls")
              and target.attr in table.defs):
            fname = target.attr          # to_static(self._train_step)
        if fname is not None:
            names, nums = _statics_from_call(node)
            sites.append(JitSite(fname, node, names, nums))
    return sites


def param_names(fn) -> list[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if a.vararg:
        names.append(a.vararg.arg)
    names += [p.arg for p in a.kwonlyargs]
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def traced_params(fn, site: JitSite) -> set:
    """Params of a jit-entry function that carry traced values: everything
    except self/cls and the declared static args."""
    a = fn.args
    pos = [p.arg for p in a.posonlyargs + a.args]
    out = set(pos) | {p.arg for p in a.kwonlyargs}
    if a.vararg:
        out.add(a.vararg.arg)
    for i in sorted(site.static_nums):
        if 0 <= i < len(pos):
            out.discard(pos[i])
    out -= site.static_names
    out -= {"self", "cls"}
    return out
