"""contracts — cross-process contract parity over the summary index.

A multi-process fleet's contracts are stringly-typed: RPC op names, fault
point names, metric family names, and the implicit "exceptions travel by
pickle" rule of the worker RPC plane.  No single file sees both halves of
any of them, so these rules run against the whole-program
:class:`~..summaries.SummaryIndex` instead of one AST:

* **CT101 — RPC op parity.**  Every constant-string op reaching an
  ``RpcClient.call`` (directly or through a forwarder like
  ``RemoteReplica._call``) must be handled by some registered dispatcher's
  ``op == "..."`` table; an op a *closed* dispatcher (one whose handler
  ends by raising on unknown ops) handles but nobody calls is dead
  protocol surface.  Files registering an *open* dispatcher (a test fake
  whose handler accepts anything) are exempt from site checks.
* **CT102 — pickle-safe RPC errors.**  An exception class raised anywhere
  in a dispatcher's import closure crosses the process boundary by value.
  That round-trips only if the class defines ``__reduce__`` or its
  ``__init__`` forwards its parameters verbatim (in order, positionally)
  to ``super().__init__`` — otherwise the server degrades it to
  ``RuntimeError(repr)`` and the client loses the type and its fields.
* **CT103 — fault-point parity.**  Every ``FAULTS.raise_if("x")`` /
  ``maybe_fire`` / ``fire`` string must appear in ``KNOWN_POINTS``
  (``testing/faults.py``), and every declared point must be fired
  somewhere and armed by at least one ``injected("x", ...)`` in the
  analyzed tree — an untested fault point is dead chaos surface.
* **CT104 — metric-family discipline.**  Family names must be literal
  (cardinality belongs in labels, not f-string names), valid Prometheus
  names, and keep one metric type per name across all modules.
"""
from __future__ import annotations

import re

from ..framework import AnalysisPass, Finding, register_pass

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")

_HINTS = {
    "CT101": "add an `op == \"...\"` arm to the worker dispatcher (or drop "
             "the dead arm); op strings are the wire protocol",
    "CT102": "give the class a __reduce__, or make __init__ forward its "
             "params verbatim to super().__init__",
    "CT103": "declare the point in testing/faults.py KNOWN_POINTS and arm "
             "it with injected(\"...\", schedule) in a chaos test",
    "CT104": "declare each family once, with a literal valid name and one "
             "type; put the varying part in labelnames",
}

_DOCS = {
    "CT101": "RPC op parity: every constant-string op reaching an "
             "RpcClient.call (directly or via a forwarder method) must "
             "have a dispatcher arm, and every closed-dispatcher arm must "
             "have a caller — op strings are the cross-process protocol, "
             "and drift on either side is invisible to unit tests.",
    "CT102": "Pickle-safe RPC errors: exceptions raised under a server "
             "handler travel to the client by pickle.  Without __reduce__ "
             "or a verbatim-forwarding __init__, the default reduce "
             "replays cls(*self.args) with the wrong arguments and the "
             "server degrades the error to RuntimeError(repr).",
    "CT103": "Fault-point parity: a FAULTS.maybe_fire/raise_if/fire string "
             "must be declared in KNOWN_POINTS and exercised by at least "
             "one injected(...) in the analyzed tree; an undeclared point "
             "is a typo magnet and an unexercised one is dead chaos "
             "surface.",
    "CT104": "Metric-family discipline: family names must be literal, "
             "valid Prometheus names, with exactly one metric type per "
             "name across every module that declares into the registry.",
}


@register_pass
class ContractsPass(AnalysisPass):
    name = "contracts"
    version = 1
    codes = ("CT101", "CT102", "CT103", "CT104")
    rule_docs = _DOCS
    rule_severities = {
        "CT101": "error (unhandled op) / warning (dead dispatcher arm)",
        "CT102": "warning",
        "CT103": "error (fired-but-undeclared) / warning (non-literal, "
                 "never-fired, or uncovered point)",
        "CT104": "error",
    }
    summary_scope = True
    summary_domains = ("rpc", "exceptions", "faults", "metrics")
    description = ("cross-process contract parity: RPC ops, pickle-safe "
                   "errors, fault points, metric families")

    def check_summaries(self, src, index) -> list[Finding]:
        findings: list[Finding] = []
        self._ct101(src, index, findings)
        self._ct102(src, index, findings)
        self._ct103(src, index, findings)
        self._ct104(src, index, findings)
        return findings

    # ---- CT101: RPC op parity ------------------------------------------------
    def _ct101(self, src, index, findings):
        if not index.has_dispatchers:
            return
        if src.path not in index.open_dispatcher_paths:
            for path, line, op in index.op_sites:
                if path != src.path or op in index.handled_ops:
                    continue
                findings.append(Finding(
                    self.name, "CT101", path, line,
                    f"RPC op {op!r} has no registered server handler — the "
                    "call raises 'unknown worker op' at runtime",
                    _HINTS["CT101"]))
        if index.has_op_sites:
            called = {op for _, _, op in index.op_sites}
            for d in index.dispatchers:
                if d["path"] != src.path or not d["closed"]:
                    continue
                for op, line in d["ops"]:
                    if op not in called:
                        findings.append(Finding(
                            self.name, "CT101", d["path"], line,
                            f"dispatcher op {op!r} has no call site anywhere "
                            "— dead protocol surface", _HINTS["CT101"],
                            severity="warning"))

    # ---- CT102: pickle-safe RPC errors ---------------------------------------
    def _ct102(self, src, index, findings):
        if not index.has_dispatchers:
            return
        for key in index.raised_in_closure:
            if key[0] != src.path or key not in index.exception_classes:
                continue
            c = index.classes[key]
            if c["has_reduce"] or c["init_safe"]:
                continue
            findings.append(Finding(
                self.name, "CT102", key[0], c["init_line"],
                f"exception {c['name']!r} is raised under the RPC dispatch "
                "closure but cannot travel by value: __init__ does not "
                "forward its args verbatim and there is no __reduce__ — it "
                "degrades to RuntimeError(repr) at the client",
                _HINTS["CT102"], severity="warning"))

    # ---- CT103: fault-point parity -------------------------------------------
    def _ct103(self, src, index, findings):
        declared = index.declared_points
        if src.path not in index.decl_paths:
            # a point this file both arms (injected/install) and fires is a
            # self-contained ad-hoc point — the injector's own unit tests do
            # this; production files never arm points, so the parity check
            # stays strict there
            summary = index.summaries.get(src.path) or {}
            self_armed = {c["point"] for c in summary.get("fault_coverage", ())
                          if c["point"] is not None}
            for path, line, api, point in index.fault_fires:
                if path != src.path:
                    continue
                if point is None:
                    findings.append(Finding(
                        self.name, "CT103", path, line,
                        f"FAULTS.{api} with a non-literal point name — "
                        "parity with KNOWN_POINTS cannot be checked",
                        _HINTS["CT103"], severity="warning"))
                elif declared and point not in declared \
                        and point not in self_armed:
                    findings.append(Finding(
                        self.name, "CT103", path, line,
                        f"fault point {point!r} is fired but not declared "
                        "in KNOWN_POINTS", _HINTS["CT103"]))
            return
        # the declaring module owns the decl-side findings
        if not index.has_outside_fires:
            return
        fired = {pt for p, _, _, pt in index.fault_fires
                 if pt is not None and p not in index.decl_paths}
        for path, line, names in index.fault_decls:
            if path != src.path:
                continue
            for n in names:
                if n not in fired:
                    findings.append(Finding(
                        self.name, "CT103", path, line,
                        f"declared fault point {n!r} is never fired — dead "
                        "chaos surface", _HINTS["CT103"],
                        severity="warning"))
                elif index.has_fault_coverage and \
                        n not in index.fault_coverage:
                    findings.append(Finding(
                        self.name, "CT103", path, line,
                        f"declared fault point {n!r} has no injected(...) "
                        "chaos coverage", _HINTS["CT103"],
                        severity="warning"))

    # ---- CT104: metric-family discipline -------------------------------------
    def _ct104(self, src, index, findings):
        for m in index.metric_decls:
            if m["path"] != src.path:
                continue
            if not m["literal"]:
                findings.append(Finding(
                    self.name, "CT104", m["path"], m["line"],
                    f"metric family declared with a non-literal name "
                    f"({m['kind']}) — computed names explode cardinality "
                    "and defeat cross-module type checks", _HINTS["CT104"]))
                continue
            name = m["metric"]
            if name is None:
                continue
            if not _NAME_RE.match(name):
                findings.append(Finding(
                    self.name, "CT104", m["path"], m["line"],
                    f"metric family {name!r} is not a valid Prometheus "
                    "name", _HINTS["CT104"]))
            first = index.metric_kinds.get(name)
            if first is not None and first["kind"] != m["kind"]:
                findings.append(Finding(
                    self.name, "CT104", m["path"], m["line"],
                    f"metric family {name!r} redeclared as {m['kind']} but "
                    f"first declared as {first['kind']} — one type per "
                    "family", _HINTS["CT104"]))
