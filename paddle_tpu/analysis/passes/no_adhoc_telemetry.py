"""no-adhoc-telemetry — keep runtime telemetry on the sanctioned channels.

This PR's observability layer gives library code three blessed outlets:
``logging`` (diagnostics), the metrics registry (counters/gauges/histograms)
and ``trace_span`` (timeline).  Ad-hoc instrumentation rots past them:

  * ``print(...)`` in library code is invisible to any collector, cannot be
    filtered by level, and interleaves with user stdout.  (AT101)
  * ``time.time()`` is *wall clock* — NTP steps and DST make it jump, so
    intervals measured with it are occasionally negative or wildly wrong.
    Durations belong to ``time.perf_counter()``; deadlines shared within a
    process to ``time.monotonic()``.  Wall-clock reads that genuinely need
    calendar time (timestamps persisted across processes) carry a line
    pragma stating so.  (AT102)
  * An RPC ``client.call(...)`` that omits the ``ctx`` keyword silently
    DROPS the request's trace context at the process boundary — the remote
    span events land in a fresh (orphaned) timeline and the fleet-merged
    chrome trace shows a hole exactly where the bug is.  Every call on a
    client-like receiver (``client`` / ``*_client`` / ``rpc``) must pass
    ``ctx=`` — ``wire_context()`` for request-scoped traffic, an explicit
    ``ctx=None`` for control-plane ops that genuinely have no trace.
    (AT103)

Pure CLI front-ends (whose job *is* printing) opt out with
``# graftlint: disable-file=no-adhoc-telemetry``.
"""
from __future__ import annotations

import ast

from ..framework import AnalysisPass, Finding, register_pass

_HINTS = {
    "AT101": "use logging (module logger) for diagnostics, or the "
             "observability registry for counters; pragma user-facing "
             "console output",
    "AT102": "time.perf_counter() for durations, time.monotonic() for "
             "deadlines; pragma genuine wall-clock (calendar) reads",
    "AT103": "pass ctx=wire_context() to thread the ambient trace through "
             "the frame, or an explicit ctx=None for untraced "
             "control-plane ops",
}

# receivers treated as RPC clients: `client.call(...)`, `self.client.call`,
# `foo_client.call`, `rpc.call`.  Purely lexical — graftlint is AST-only —
# so a non-RPC object that happens to be named `client` needs a line pragma.
def _is_client_receiver(expr):
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return False
    name = name.lower().lstrip("_")
    return name == "rpc" or name == "client" or name.endswith("_client")


@register_pass
class NoAdhocTelemetryPass(AnalysisPass):
    name = "no-adhoc-telemetry"
    version = 2
    codes = ("AT101", "AT102", "AT103")
    description = ("bare print(), wall-clock time.time() timing, and RPC "
                   "client.call() sites that drop the trace-context field")

    def check_file(self, src) -> list[Finding]:
        findings: list[Finding] = []
        # `from time import time [as t]` makes bare-name calls wall-clock too
        time_aliases = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name == "time":
                        time_aliases.add(a.asname or a.name)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id == "print":
                findings.append(Finding(
                    self.name, "AT101", src.path, node.lineno,
                    "bare print() in library code — uncollectable, "
                    "unfilterable telemetry", _HINTS["AT101"]))
            elif (isinstance(f, ast.Attribute) and f.attr == "time"
                  and isinstance(f.value, ast.Name) and f.value.id == "time"):
                findings.append(Finding(
                    self.name, "AT102", src.path, node.lineno,
                    "time.time() is wall clock — intervals jump on NTP "
                    "steps", _HINTS["AT102"]))
            elif isinstance(f, ast.Name) and f.id in time_aliases:
                findings.append(Finding(
                    self.name, "AT102", src.path, node.lineno,
                    f"{f.id}() (time.time) is wall clock — intervals jump "
                    "on NTP steps", _HINTS["AT102"]))
            elif (isinstance(f, ast.Attribute) and f.attr == "call"
                  and _is_client_receiver(f.value)
                  and not any(k.arg == "ctx" for k in node.keywords)):
                findings.append(Finding(
                    self.name, "AT103", src.path, node.lineno,
                    "RpcClient.call without ctx= drops the request's trace "
                    "context at the process boundary — remote spans orphan "
                    "into a fresh timeline", _HINTS["AT103"]))
        return findings
