"""jit-cache-hygiene — catch idioms that silently recompile per call.

``jax.jit``'s compile cache is keyed on static-arg *values* and abstract
shapes.  A tensor-valued default argument is a fresh object every trace; an
unhashable (list/dict/set) value for a declared static arg either raises or,
when wrapped, recompiles on every call.  Both degrade "compiled once" into
"compiled always" with no functional symptom — only latency.

  * JH001 mutable (list/dict/set) default argument on a jit entry
  * JH002 tensor/array-valued default argument on a jit entry
  * JH003 container literal passed for a declared static arg at a call site
  * JH004 declared static arg whose default is an unhashable container
"""
from __future__ import annotations

import ast

from ..framework import AnalysisPass, Finding, register_pass
from ._jit import FunctionTable, collect_jit_sites, dotted

_MUTABLE_NODES = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                  ast.SetComp)
# call prefixes whose result is array-valued: a fresh object per trace
_ARRAY_FACTORIES = ("np.", "numpy.", "jnp.", "jax.numpy.")
_ARRAY_CALLS = {"to_tensor", "zeros", "ones", "array", "asarray", "arange",
                "full", "empty", "tensor"}


def _is_array_default(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func)
    if d is None:
        return False
    last = d.rsplit(".", 1)[-1]
    return d.startswith(_ARRAY_FACTORIES) or last in _ARRAY_CALLS


def _defaults(fn):
    """[(param_name, default_node)] for every defaulted parameter."""
    a = fn.args
    pos = a.posonlyargs + a.args
    out = list(zip([p.arg for p in pos[len(pos) - len(a.defaults):]],
                   a.defaults))
    out += [(p.arg, d) for p, d in zip(a.kwonlyargs, a.kw_defaults)
            if d is not None]
    return out


@register_pass
class JitCacheHygienePass(AnalysisPass):
    name = "jit-cache-hygiene"
    version = 1
    codes = ("JH001", "JH002", "JH003", "JH004")
    description = ("unhashable/tensor-valued defaults and non-static "
                   "containers as static args on jit entries")

    def check_file(self, src) -> list[Finding]:
        table = FunctionTable()
        table.visit(src.tree)
        sites = collect_jit_sites(src.tree, table)
        if not sites:
            return []
        findings: list[Finding] = []
        seen = set()

        def emit(line, code, msg, hint):
            if (line, code) in seen:
                return
            seen.add((line, code))
            findings.append(Finding(self.name, code, src.path, line, msg,
                                    hint))

        statics_of: dict[str, set] = {}
        for site in sites:
            fn = table.defs.get(site.func_name or "")
            if fn is None:
                continue
            a = fn.args
            pos = [p.arg for p in a.posonlyargs + a.args]
            statics = set(site.static_names)
            for i in site.static_nums:
                if 0 <= i < len(pos):
                    statics.add(pos[i])
            statics_of.setdefault(fn.name, set()).update(statics)
            for pname, default in _defaults(fn):
                if isinstance(default, _MUTABLE_NODES):
                    code = "JH004" if pname in statics else "JH001"
                    what = ("static arg with unhashable container default"
                            if pname in statics else
                            "mutable container default")
                    emit(default.lineno, code,
                         f"jit entry '{fn.name}' param '{pname}': {what} — "
                         "hashing fails or every call recompiles",
                         "use None + an in-function default, or a tuple")
                elif _is_array_default(default):
                    emit(default.lineno, "JH002",
                         f"jit entry '{fn.name}' param '{pname}' defaults "
                         "to a fresh array per call — each trace sees a new "
                         "object and recompiles",
                         "hoist the array to a module constant or pass it "
                         "explicitly")
        # call sites passing container literals for declared static args
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = node.func.id if isinstance(node.func, ast.Name) else None
            statics = statics_of.get(fname or "")
            if not statics:
                continue
            fn = table.defs[fname]
            a = fn.args
            pos = [p.arg for p in a.posonlyargs + a.args]
            for i, arg in enumerate(node.args):
                if i < len(pos) and pos[i] in statics \
                        and isinstance(arg, _MUTABLE_NODES):
                    emit(arg.lineno, "JH003",
                         f"call passes a {type(arg).__name__.lower()} for "
                         f"static arg '{pos[i]}' of '{fname}' — unhashable "
                         "static values recompile (or fail) per call",
                         "pass a tuple/scalar, or drop it from static args")
            for kw in node.keywords:
                if kw.arg in statics and isinstance(kw.value, _MUTABLE_NODES):
                    emit(kw.value.lineno, "JH003",
                         f"call passes a {type(kw.value).__name__.lower()} "
                         f"for static arg '{kw.arg}' of '{fname}' — "
                         "unhashable static values recompile (or fail) per "
                         "call",
                         "pass a tuple/scalar, or drop it from static args")
        return findings
