"""graftlint passes — importing this package registers every built-in pass."""
from . import (concurrency, dtype_rules, jit_cache_hygiene,  # noqa: F401
               namespace_parity, no_adhoc_telemetry, registry_parity,
               robustness, sharding_spec, trace_safety)

__all__ = ["concurrency", "dtype_rules", "jit_cache_hygiene",
           "namespace_parity", "no_adhoc_telemetry", "registry_parity",
           "robustness", "sharding_spec", "trace_safety"]
