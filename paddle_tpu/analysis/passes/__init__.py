"""graftlint passes — importing this package registers every built-in pass."""
from . import (dtype_rules, jit_cache_hygiene, namespace_parity,  # noqa: F401
               no_adhoc_telemetry, registry_parity, robustness,
               sharding_spec, trace_safety)

__all__ = ["dtype_rules", "jit_cache_hygiene", "namespace_parity",
           "no_adhoc_telemetry", "registry_parity", "robustness",
           "sharding_spec", "trace_safety"]
