"""graftlint passes — importing this package registers every built-in pass."""
from . import (concurrency, contracts, dtype_rules,  # noqa: F401
               jit_cache_hygiene, namespace_parity, no_adhoc_telemetry,
               registry_parity, resource_lifecycle, robustness,
               sharding_spec, trace_safety)

__all__ = ["concurrency", "contracts", "dtype_rules", "jit_cache_hygiene",
           "namespace_parity", "no_adhoc_telemetry", "registry_parity",
           "resource_lifecycle", "robustness", "sharding_spec",
           "trace_safety"]
