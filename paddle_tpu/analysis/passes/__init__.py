"""graftlint passes — importing this package registers every built-in pass."""
from . import (jit_cache_hygiene, namespace_parity,  # noqa: F401
               no_adhoc_telemetry, registry_parity, trace_safety)

__all__ = ["jit_cache_hygiene", "namespace_parity", "no_adhoc_telemetry",
           "registry_parity", "trace_safety"]
