"""dtype-rules — keep the op table's dtypes honest against ``core/dtype.py``.

The promotion contract: ``convert_dtype`` silently narrows 64-bit requests
(int64 -> int32, float64 -> float32, uint64 -> uint32, complex128 ->
complex64) whenever JAX x64 is off — the TPU default.  That means a sample
builder that hands the suite an int64 index array is lying: the numpy golden
computes with 64-bit inputs while the op under test sees the narrowed 32-bit
tensor, and the comparison only passes until a value crosses the narrower
range.  Same story for a float64 golden output silently down-cast before the
assert.

Like registry-parity, this pass has a static half (map registrations to
lines) and a runtime half (import the live registry, build each sample, run
the numpy reference) — so it is project-scoped and never cached.

Checks (codes):

  * DT101 sample/kwargs array dtype that ``convert_dtype`` would narrow
          (the op computes on different bits than the golden)
  * DT102 numpy reference returns float64/complex128 from <=32-bit floating
          inputs — the comparison down-casts and hides precision drift
          [warning]
  * DT103 ``grad=True`` with no floating-point sample input: the
          finite-difference grad check cannot perturb integers
"""
from __future__ import annotations

import ast
import importlib

import numpy as np

from ..framework import AnalysisPass, Finding, Project, register_pass

_HELPERS = {"u", "b", "g", "smoke"}

_HINTS = {
    "DT101": "build the sample in the narrowed dtype directly (e.g. "
             "np.int32 index arrays) so golden and op see the same bits",
    "DT102": "cast the reference output (.astype) to the widest input "
             "dtype, or accept the masked precision via the baseline",
    "DT103": "give the op a floating sample input, or register it with "
             "grad=False",
}

# float dtypes at or below 32 bits (includes the ml_dtypes small floats)
_NARROW_FLOAT_BITS = 32


def _convert_dtype():
    from ...core.dtype import convert_dtype
    return convert_dtype


def _is_floating(dt) -> bool:
    from ...core.dtype import is_floating_point
    try:
        return is_floating_point(dt)
    except TypeError:
        return False


def _arrays(obj):
    """Flatten ndarray leaves out of samples/kwargs values."""
    if isinstance(obj, np.ndarray):
        yield obj
    elif isinstance(obj, (list, tuple)):
        for x in obj:
            yield from _arrays(x)
    elif isinstance(obj, dict):
        for x in obj.values():
            yield from _arrays(x)


@register_pass
class DtypeRulesPass(AnalysisPass):
    name = "dtype-rules"
    version = 1
    codes = ("DT101", "DT102", "DT103")
    description = ("op-table dtype checks against core.dtype promotion: "
                   "64-bit samples that narrow, float64 goldens, "
                   "non-differentiable grad samples")
    project_scope = True    # runtime half imports the live registry

    def check_project(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for src in project.files:
            lines = self._registration_lines(src)
            if not lines:
                continue
            mod = Project.module_name(src.path)
            if mod is None:
                continue
            try:
                live = importlib.import_module(mod)
            except Exception:
                continue    # registry-parity already reports RP006
            if not hasattr(live, "REGISTRY"):
                continue
            findings.extend(self._check_registry(src, live, lines))
        return findings

    # ---- static half: op name -> registration line -----------------------
    @staticmethod
    def _registration_lines(src):
        mentions = {n.id for n in ast.walk(src.tree)
                    if isinstance(n, ast.Name)}
        if not {"REGISTRY", "OpSpec"} & mentions:
            return {}
        lines = {}
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                    and node.func.id in _HELPERS and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                lines.setdefault(node.args[0].value, node.lineno)
        return lines

    # ---- runtime half ----------------------------------------------------
    def _check_registry(self, src, live, lines):
        findings = []
        convert = _convert_dtype()

        def emit(name, code, msg, severity="error"):
            findings.append(Finding(self.name, code, src.path,
                                    lines.get(name, 1), msg, _HINTS[code],
                                    severity))

        for name, spec in live.REGISTRY.items():
            if getattr(spec, "kind", None) in ("alias", "inplace"):
                continue
            if spec.sample is None:
                continue
            try:
                sample = spec.sample()
            except Exception:
                continue    # registry-parity already reports RP008
            arrays = list(_arrays(sample))
            kw_arrays = list(_arrays(getattr(spec, "kwargs", {}) or {}))

            # DT101: inputs the tensor layer would silently narrow
            for where, arrs in (("sample", arrays), ("kwargs", kw_arrays)):
                flagged = set()
                for a in arrs:
                    narrowed = convert(a.dtype)
                    if narrowed != a.dtype and a.dtype not in flagged:
                        flagged.add(a.dtype)
                        emit(name, "DT101",
                             f"op '{name}' {where} array is {a.dtype} but "
                             f"convert_dtype narrows it to {narrowed} — "
                             "the golden and the op compute on different "
                             "dtypes")

            # DT103: grad check needs something to perturb
            if getattr(spec, "grad", False) and arrays \
                    and not any(_is_floating(a.dtype) for a in arrays):
                emit(name, "DT103",
                     f"op '{name}' has grad=True but no floating-point "
                     "sample input — finite differences cannot perturb "
                     f"{'/'.join(sorted({str(a.dtype) for a in arrays}))}")

            # DT102: float64 golden from narrow floating inputs
            if spec.np_ref is None or not arrays:
                continue
            floats = [a for a in arrays if _is_floating(a.dtype)]
            if not floats or any(a.dtype.itemsize * 8 > _NARROW_FLOAT_BITS
                                 for a in floats):
                continue
            try:
                out = spec.np_ref(*sample)
            except Exception:
                continue    # suite-level failure, not a dtype finding
            for o in _arrays(out if isinstance(out, (list, tuple)) else [out]):
                if o.dtype in (np.dtype(np.float64), np.dtype(np.complex128)):
                    emit(name, "DT102",
                         f"op '{name}' numpy reference returns {o.dtype} "
                         "from <=32-bit floating inputs — the comparison "
                         "down-casts and can mask drift",
                         severity="warning")
                    break
        return findings
