"""sharding-spec-coverage — SPMD contract checks at every ``shard_map`` site.

The bug class: ``shard_map`` takes the sharding contract (mesh, in_specs,
out_specs) as *data*, so nothing checks it until the traced function runs on
a real multi-device mesh — which unit tests on one chip never do.  A spec
tuple one entry short, an axis name that isn't in the mesh, or a collective
whose ``axis_name`` the surrounding shard_map never binds all surface as
cryptic runtime errors (or, worst, as a deadlock: a collective under a
data-dependent branch runs on some shards and not others, and the program
hangs at the next synchronization point).

Checks (codes):

  * SS101 in_specs arity != the wrapped function's free positional arity
          (body resolved through local defs, lambdas, ``functools.partial``
          and cross-file imports via :mod:`..resolve`)
  * SS102 literal PartitionSpec axis name not among the mesh's axis names
          (only when the mesh constructor's axis names are literal)
  * SS103 collective called inside the body with a literal ``axis_name``
          the surrounding shard_map's mesh does not bind
  * SS104 collective under data-dependent control flow (an ``if``/``while``
          whose test depends on a traced body parameter): SPMD divergence —
          shards that skip the collective deadlock the ones that don't
          [warning]
  * SS105 out_specs tuple arity != the body's returned tuple arity
  * SS106 ``NamedSharding(mesh, spec)`` (any site — direct, inside
          ``with_sharding_constraint``, ``jax.device_put``, ...) whose
          literal PartitionSpec names an axis the (literal) mesh does not
          define; also bare PartitionSpec values passed through
          ``jax.jit(..., in_shardings=/out_shardings=)`` keywords, resolved
          against the mesh of the lexically enclosing ``with <mesh>:`` /
          ``with use_mesh(mesh):`` block

Everything literal-or-resolvable is checked; dynamic specs/meshes/axis names
are skipped, never guessed — a lint finding here should always be real.
"""
from __future__ import annotations

import ast

from ..framework import AnalysisPass, Finding, Project, register_pass
from ..resolve import (Imports, collective_axis_arg, is_jit,
                       is_named_sharding, is_partition_spec, is_shard_map,
                       mesh_axis_names, _literal_axis_names)
from .trace_safety import _is_tainted, _scan, _target_names

_HINTS = {
    "SS101": "make in_specs one spec per body parameter (bind extras with "
             "functools.partial, or pass a single spec for a pytree arg)",
    "SS102": "use an axis name the mesh declares, or add the axis to the "
             "mesh constructor",
    "SS103": "collectives inside shard_map may only name mesh axes the "
             "shard_map binds; fix the axis_name or the mesh",
    "SS104": "hoist the collective out of the branch, or rewrite with "
             "jnp.where/lax.cond so every shard executes it",
    "SS105": "return one value per out_specs entry (or collapse out_specs "
             "to a single spec for a pytree result)",
    "SS106": "NamedSharding specs may only name axes its mesh defines; fix "
             "the PartitionSpec axis or add the axis to the mesh",
}

_PARTIAL = ("functools.partial", "partial")


def _kwarg(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _positional_params(fn):
    a = fn.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


class _Body:
    """A resolved shard_map body: the def/lambda node, the file it lives in,
    and how many leading positionals / which keywords ``partial`` bound."""

    def __init__(self, fn, src):
        self.fn = fn
        self.src = src
        self.bound_pos = 0
        self.bound_kw: set[str] = set()

    def free_positional(self):
        names = _positional_params(self.fn)
        if names and names[0] in ("self", "cls"):
            names = names[1:]
        names = names[self.bound_pos:]
        return [n for n in names if n not in self.bound_kw]

    def has_var_positional(self):
        return self.fn.args.vararg is not None


def _spec_axes(node, imports):
    """[(axis_name, line)] for every literal axis string inside a
    PartitionSpec call anywhere under ``node``."""
    out = []
    if node is None:
        return out
    for n in ast.walk(node):
        if not (isinstance(n, ast.Call)
                and is_partition_spec(imports.canonical(n.func))):
            continue
        for a in list(n.args) + [kw.value for kw in n.keywords]:
            names = _literal_axis_names(a)
            for name in names or ():
                out.append((name, n.lineno))
    return out


@register_pass
class ShardingSpecPass(AnalysisPass):
    name = "sharding-spec-coverage"
    version = 3
    codes = ("SS101", "SS102", "SS103", "SS104", "SS105", "SS106")
    description = ("shard_map contract checks: in/out_specs arity, spec and "
                   "collective axis names vs the mesh, collectives under "
                   "data-dependent control flow, NamedSharding/"
                   "with_sharding_constraint/jit-shardings spec-vs-mesh "
                   "axis validity")
    project_scope = True    # resolves bodies across files

    def check_project(self, project: Project) -> list[Finding]:
        # cross-file function index: every file's top-level defs, keyed by
        # dotted module name when importable, always by basename stem
        self._funcs: dict[str, dict] = {}
        self._imports: dict[str, Imports] = {}
        for src in project.files:
            defs = {n.name: (n, src) for n in src.tree.body
                    if isinstance(n, ast.FunctionDef)}
            if not defs:
                continue
            mod = Project.module_name(src.path)
            if mod:
                self._funcs[mod] = defs
            stem = src.path.replace("\\", "/").rsplit("/", 1)[-1][:-3]
            self._funcs.setdefault(stem, {}).update(defs)
        findings: list[Finding] = []
        for src in project.files:
            imports = self._file_imports(src)
            self._walk(src.tree, [], src, imports, findings)
        return findings

    def _file_imports(self, src) -> Imports:
        if src.path not in self._imports:
            self._imports[src.path] = Imports(
                src.tree, Project.module_name(src.path))
        return self._imports[src.path]

    # ---- traversal -------------------------------------------------------
    def _walk(self, node, scopes, src, imports, findings, mesh_ctx=None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call):
                canon = imports.canonical(child.func)
                if is_shard_map(canon):
                    self._check_site(child, scopes, src, imports, findings)
                elif is_named_sharding(canon):
                    # covers every construction site: with_sharding_constraint
                    # / device_put arguments are visited by this same walk
                    self._check_named_sharding(child, scopes, src, imports,
                                               findings)
                elif is_jit(canon):
                    self._check_jit_shardings(child, src, imports, findings,
                                              mesh_ctx)
            if isinstance(child, (ast.With, ast.AsyncWith)):
                ctx = mesh_ctx
                for item in child.items:
                    axes = self._with_mesh_axes(item.context_expr, scopes,
                                                src)
                    if axes is not None:
                        ctx = axes
                self._walk(child, scopes, src, imports, findings, ctx)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(child, [child] + scopes, src, imports, findings,
                           mesh_ctx)
            else:
                self._walk(child, scopes, src, imports, findings, mesh_ctx)

    def _with_mesh_axes(self, expr, scopes, src):
        """Axis names a ``with`` item puts in scope: ``with mesh:`` /
        ``with Mesh(devs, (...)):`` / ``with use_mesh(mesh):`` when the mesh
        is statically known, else None."""
        axes = self._mesh_axes(expr, scopes, src)
        if axes is not None:
            return axes
        if isinstance(expr, ast.Call) and expr.args:
            canon = self._file_imports(src).canonical(expr.func)
            if canon and (canon == "use_mesh"
                          or canon.endswith(".use_mesh")
                          or canon.endswith(".set_mesh")):
                return self._mesh_axes(expr.args[0], scopes, src)
        return None

    # ---- body / mesh resolution ------------------------------------------
    def _lookup_name(self, name, scopes, src):
        """Resolve ``name`` at a call site: nested defs and assignments in
        enclosing scopes (innermost first), then module level."""
        spaces = [fn.body for fn in scopes] + [src.tree.body]
        for body in spaces:
            for stmt in body:
                if isinstance(stmt, ast.FunctionDef) and stmt.name == name:
                    return stmt
                if isinstance(stmt, ast.Assign) \
                        and name in _target_names(stmt.targets[0]) \
                        and len(stmt.targets) == 1:
                    return stmt.value
        return None

    def _resolve_body(self, node, scopes, src, depth=0):
        if node is None or depth > 8:
            return None
        if isinstance(node, ast.Lambda):
            return _Body(node, src)
        imports = self._file_imports(src)
        if isinstance(node, ast.Call):
            canon = imports.canonical(node.func)
            if canon in _PARTIAL or (canon and canon.endswith(".partial")):
                inner = self._resolve_body(
                    node.args[0] if node.args else None, scopes, src,
                    depth + 1)
                if inner is None:
                    return None
                inner.bound_pos += len(node.args) - 1
                inner.bound_kw |= {kw.arg for kw in node.keywords if kw.arg}
                return inner
            return None
        if isinstance(node, ast.Name):
            local = self._lookup_name(node.id, scopes, src)
            if isinstance(local, ast.FunctionDef):
                return _Body(local, src)
            if local is not None:
                return self._resolve_body(local, scopes, src, depth + 1)
        # fall through to cross-file: canonical path -> another file's def
        canon = imports.canonical(node)
        if canon and "." in canon:
            mod, fname = canon.rsplit(".", 1)
            for key, defs in self._funcs.items():
                if (key == mod or key.endswith("." + mod)) and fname in defs:
                    fn, fsrc = defs[fname]
                    return _Body(fn, fsrc)
        return None

    def _mesh_axes(self, node, scopes, src):
        """Mesh axis names when statically known, else None."""
        imports = self._file_imports(src)
        for _ in range(4):                    # chase simple assignments
            if isinstance(node, ast.Call):
                return mesh_axis_names(node, imports)
            if isinstance(node, ast.Name):
                node = self._lookup_name(node.id, scopes, src)
                if isinstance(node, ast.FunctionDef):
                    return None
                continue
            return None
        return None

    # ---- per-site checks -------------------------------------------------
    def _check_site(self, call, scopes, src, imports, findings):
        def arg(i, kw):
            node = _kwarg(call, kw)
            return node if node is not None else (
                call.args[i] if len(call.args) > i else None)

        f_node = call.args[0] if call.args else _kwarg(call, "f")
        mesh_node = arg(1, "mesh")
        in_node = arg(2, "in_specs")
        out_node = arg(3, "out_specs")

        def emit(code, line, msg, severity="error"):
            findings.append(Finding(self.name, code, src.path, line, msg,
                                    _HINTS[code], severity))

        mesh_axes = self._mesh_axes(mesh_node, scopes, src)
        body = self._resolve_body(f_node, scopes, src)

        # SS101: in_specs tuple arity vs the body's free positional params
        if body is not None and isinstance(in_node, (ast.Tuple, ast.List)) \
                and not body.has_var_positional():
            free = body.free_positional()
            if len(free) != len(in_node.elts):
                emit("SS101", call.lineno,
                     f"in_specs has {len(in_node.elts)} spec(s) but the "
                     f"shard_map body takes {len(free)} positional "
                     f"argument(s) ({', '.join(free) or 'none'})")

        # SS102: literal spec axis names must exist on the (literal) mesh
        if mesh_axes is not None:
            for name, line in (_spec_axes(in_node, imports)
                               + _spec_axes(out_node, imports)):
                if name not in mesh_axes:
                    emit("SS102", line,
                         f"PartitionSpec names axis '{name}' but the mesh "
                         f"only defines ({', '.join(mesh_axes)})")

        # SS105: out_specs tuple arity vs literal tuple returns
        if body is not None and isinstance(out_node, (ast.Tuple, ast.List)) \
                and isinstance(body.fn, ast.FunctionDef):
            arity = self._return_tuple_arity(body.fn)
            if arity is not None and arity != len(out_node.elts):
                emit("SS105", call.lineno,
                     f"out_specs has {len(out_node.elts)} spec(s) but the "
                     f"body returns a {arity}-tuple")

        if body is not None:
            self._sweep_body(body, mesh_axes, emit)

    def _check_named_sharding(self, call, scopes, src, imports, findings):
        """SS106: NamedSharding(mesh, spec) whose literal spec names an axis
        the (literal) mesh does not define.  Same skip-don't-guess policy as
        the shard_map checks: either side dynamic -> no finding."""
        mesh_node = call.args[0] if call.args else _kwarg(call, "mesh")
        spec_node = (call.args[1] if len(call.args) > 1
                     else _kwarg(call, "spec"))
        mesh_axes = self._mesh_axes(mesh_node, scopes, src)
        if mesh_axes is None or spec_node is None:
            return
        for name, line in _spec_axes(spec_node, imports):
            if name not in mesh_axes:
                findings.append(Finding(
                    self.name, "SS106", src.path, line,
                    f"NamedSharding spec names axis '{name}' but its mesh "
                    f"only defines ({', '.join(mesh_axes)})",
                    _HINTS["SS106"], "error"))

    def _check_jit_shardings(self, call, src, imports, findings, mesh_axes):
        """SS106, jit keyword path: bare PartitionSpec values in
        ``jax.jit(..., in_shardings=/out_shardings=)`` resolve against the
        mesh active at trace time; lexically that is the enclosing ``with
        <mesh>:`` block.  No statically-known enclosing mesh -> no finding
        (skip, don't guess).  NamedSharding values carry their own mesh and
        are validated at their construction site by the normal walk."""
        if mesh_axes is None:
            return
        for kw in call.keywords:
            if kw.arg not in ("in_shardings", "out_shardings"):
                continue
            for name, line in self._bare_spec_axes(kw.value, imports):
                if name not in mesh_axes:
                    findings.append(Finding(
                        self.name, "SS106", src.path, line,
                        f"jit {kw.arg} PartitionSpec names axis '{name}' "
                        f"but the enclosing mesh context only defines "
                        f"({', '.join(mesh_axes)})",
                        _HINTS["SS106"], "error"))

    @staticmethod
    def _bare_spec_axes(node, imports):
        """[(axis, line)] for literal axis strings in PartitionSpec calls
        under ``node``, pruning NamedSharding(...) subtrees (their specs are
        checked against their own mesh, not the context one)."""
        out = []
        stack = [node] if node is not None else []
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Call):
                canon = imports.canonical(n.func)
                if is_named_sharding(canon):
                    continue
                if is_partition_spec(canon):
                    for a in list(n.args) + [kw.value for kw in n.keywords]:
                        for name in _literal_axis_names(a) or ():
                            out.append((name, n.lineno))
                    continue
            stack.extend(ast.iter_child_nodes(n))
        return out

    @staticmethod
    def _return_tuple_arity(fn):
        """Common tuple arity of the body's own return statements when every
        one returns a tuple literal; None otherwise (pytrees, vars, ...)."""
        arities = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue
            if isinstance(node, ast.Return) and node.value is not None:
                if not isinstance(node.value, ast.Tuple):
                    return None
                arities.add(len(node.value.elts))
        return arities.pop() if len(arities) == 1 else None

    # ---- body interior: SS103 + SS104 ------------------------------------
    def _sweep_body(self, body, mesh_axes, emit):
        imports = self._file_imports(body.src)
        # taint: the traced (spec-covered) params, propagated through simple
        # assignments; shape/dtype metadata reads stay static (see _scan)
        tainted = set(body.free_positional())
        for _ in range(2):
            before = len(tainted)
            for node in ast.walk(body.fn):
                if isinstance(node, ast.Assign) \
                        and _is_tainted(node.value, tainted):
                    for t in node.targets:
                        tainted.update(_target_names(t))
            if len(tainted) == before:
                break

        divergent_lines = set()
        for node in ast.walk(body.fn):
            if isinstance(node, (ast.If, ast.While)):
                uses: list = []
                _scan(node.test, tainted, uses, taint_mode=False)
                if not uses:
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and collective_axis_arg(
                            imports.canonical(sub.func)) is not None:
                        if sub.lineno not in divergent_lines:
                            divergent_lines.add(sub.lineno)
                            kind = ("while" if isinstance(node, ast.While)
                                    else "if")
                            emit("SS104", sub.lineno,
                                 f"collective under a data-dependent `{kind}`"
                                 f" on traced value '{uses[0].id}' — shards "
                                 "that skip it deadlock the ones that don't",
                                 severity="warning")

        for node in ast.walk(body.fn):
            if not isinstance(node, ast.Call):
                continue
            idx = collective_axis_arg(imports.canonical(node.func))
            if idx is None:
                continue
            axis_node = (node.args[idx] if len(node.args) > idx
                         else _kwarg(node, "axis_name"))
            names = _literal_axis_names(axis_node)
            if names is None or mesh_axes is None:
                continue
            for name in names:
                if name not in mesh_axes:
                    emit("SS103", node.lineno,
                         f"collective names axis '{name}' but the enclosing "
                         f"shard_map mesh only binds "
                         f"({', '.join(mesh_axes)})")
