"""robustness — swallowed-exception and orphan-thread hygiene.

A broad handler whose whole body is ``pass`` discards every failure — the
archetypal fault-tolerance anti-pattern this PR's serving work is built to
avoid (quarantine records the error on the request; the watchdog counts its
expiries; the retry helper re-raises after backoff).  Flagged:

  * ``except: pass`` / ``except Exception: pass`` /
    ``except BaseException: pass`` (``...`` counts as ``pass``).  (RB101)
  * a broad handler whose whole body is a bare control-flow escape —
    ``continue``, ``break``, ``return`` / ``return None`` — the loop-shaped
    variant of the same swallow: the failure vanishes AND the iteration's
    work silently disappears with it.  (RB102)
  * a non-daemon ``threading.Thread(...)`` that is never ``join()``ed (nor
    later marked daemon): library code that starts one leaks a thread that
    blocks interpreter exit and outlives every ``close()``.  The fleet's
    worker/supervisor/heartbeat threads are the motivating consumers: each
    is ``daemon=True`` AND joined on its shutdown path.  (RB103)
  * a bare ``time.sleep(...)`` inside a retry loop — a ``while``/``for``
    whose body both attempts a call under ``try``/``except`` and sleeps
    between attempts.  That is a hand-rolled retry with a flat, unjittered,
    uncounted backoff; ``core/retry.py`` (``RetryPolicy`` + ``retry_call``)
    is the shared policy such loops bypass: capped exponential backoff,
    seeded jitter against stampedes, attempt telemetry.  (RB104)
  * ``open(path, "w")`` to a FINAL path inside a persistence module — one
    that elsewhere calls ``os.replace``/``os.fsync``, i.e. code that already
    knows the atomic write discipline.  A create-truncate write to the real
    destination tears on crash: readers see an empty or half file.  The
    module's own idiom is the fix — write a ``*.tmp`` sibling, flush +
    fsync, ``os.replace`` onto the final name, fsync the directory (the
    request journal's compaction and the analysis cache are in-tree
    models).  (RB105)

Narrow handlers (``except KeyError: continue``) are idiomatic probing and
stay silent, as are broad handlers that do anything observable (log, count,
record) before escaping.  A thread constructed with ``daemon=True`` (or a
non-literal ``daemon=`` the pass can't evaluate) passes RB103, as does any
thread whose storage target is joined somewhere in its enclosing class or
function.  RB104 only fires on the literal ``time.sleep`` spelling inside a
loop that also catches an attempt's failure: wait/poll loops with no
``try`` (drain loops, boot-readiness spins) stay silent, and so does code
taking an injectable ``sleep=`` callable — ``retry_call`` itself sleeps
through its injected parameter, never ``time.sleep`` directly.  RB105 is
scoped to modules that already use ``os.replace``/``os.fsync`` (pure
config-dump scripts with no durability pretensions stay silent), skips
append modes (``"a"``/``"ab"`` never truncate), non-literal modes, and
any path whose expression mentions tmp/temp — the staging file of the
idiom itself.  Deliberate exceptions carry a line pragma or a baseline
entry.
"""
from __future__ import annotations

import ast

from ..framework import AnalysisPass, Finding, register_pass

_HINT = ("handle the error, re-raise, or log it (module logger / "
         "observability registry); a deliberate swallow names the narrow "
         "exception it expects or carries a pragma")

_THREAD_HINT = ("pass daemon=True at construction, or join() the thread on "
                "the owner's shutdown path (close/stop); do both for "
                "threads that must not outlive their owner")

_RETRY_HINT = ("use core.retry.retry_call / RetryPolicy (capped exponential "
               "backoff, seeded jitter, attempt telemetry) instead of a "
               "hand-rolled sleep loop; a deliberate flat-sleep loop "
               "carries a pragma or baseline entry")

_ATOMIC_HINT = ("write to a '<name>.tmp' sibling, flush + os.fsync, then "
                "os.replace onto the final path (and fsync the directory); "
                "a deliberately torn-tolerant write carries a pragma or "
                "baseline entry")

_BROAD = ("Exception", "BaseException")

# open() modes that create-or-truncate their target; "a"/"ab" append and
# "r"/"rb" read, neither can tear an existing file's contents on crash
_TRUNCATING = ("w", "x")

_TMPISH = ("tmp", "temp")


def _is_broad(handler):
    t = handler.type
    if t is None:                                        # bare except:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _swallows(handler):
    if len(handler.body) != 1:
        return False
    stmt = handler.body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


def _escapes(handler):
    """Body is a single bare control-flow escape: the RB102 shape.  A
    ``return <value>`` (other than an explicit None) communicates something
    to the caller, so it does not count."""
    if len(handler.body) != 1:
        return False
    stmt = handler.body[0]
    if isinstance(stmt, (ast.Continue, ast.Break)):
        return type(stmt).__name__.lower()
    if isinstance(stmt, ast.Return):
        if stmt.value is None or (isinstance(stmt.value, ast.Constant)
                                  and stmt.value.value is None):
            return "return"
    return False


def _loop_scope_walk(loop):
    """Walk a loop body without crossing into nested loops' or nested
    defs' bodies: a closure defined inside the loop sleeping on its own
    schedule is not THIS loop retrying, and an inner loop gets its own
    RB104 decision."""
    stack = list(loop.body)          # orelse is the no-break exit, not a turn
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor,
                             ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_time_sleep(call):
    """The literal ``time.sleep(...)`` spelling only: an injectable
    ``sleep=`` callable (core.retry's own discipline) never matches."""
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "sleep"
            and isinstance(f.value, ast.Name) and f.value.id == "time")


def _retry_sleeps(loop):
    """RB104 sites in ``loop``: the ``time.sleep`` calls of a loop body
    that also attempts a call under ``try``/``except`` — the shape of a
    hand-rolled retry.  A sleeping loop with no handler (drain/poll spin)
    yields nothing."""
    sleeps, attempts = [], False
    for node in _loop_scope_walk(loop):
        if isinstance(node, ast.Call) and _is_time_sleep(node):
            sleeps.append(node)
        elif isinstance(node, ast.Try) and node.handlers and any(
                isinstance(c, ast.Call)
                for stmt in node.body for c in ast.walk(stmt)):
            attempts = True
    return sleeps if attempts else []


def _is_os_call(call, names):
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr in names
            and isinstance(f.value, ast.Name) and f.value.id == "os")


def _persistence_module(tree):
    """True when the module calls ``os.replace`` or ``os.fsync`` anywhere —
    it participates in the atomic-write discipline, so a create-truncate
    write to a final path elsewhere in it is an oversight, not a style."""
    return any(isinstance(n, ast.Call) and _is_os_call(n, ("replace",
                                                           "fsync"))
               for n in ast.walk(tree))


def _open_truncates(call):
    """The literal mode string of an ``open(...)`` call when it creates or
    truncates (``w``/``x`` family), else None.  A missing mode reads, a
    non-literal mode gets the benefit of the doubt."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not isinstance(mode, ast.Constant) or not isinstance(mode.value, str):
        return None
    return mode.value if mode.value[:1] in _TRUNCATING else None


def _tmpish_path(call):
    """True when the path argument's expression mentions tmp/temp anywhere
    — a string constant (``name + ".tmp"``), an identifier (``tmp_path``),
    or an attribute (``self._tmp``): the staging file of the atomic idiom,
    which RB105 must not flag."""
    if not call.args:
        return True                 # open() with kw-only path: stay silent
    for node in ast.walk(call.args[0]):
        text = None
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            text = node.value
        elif isinstance(node, ast.Name):
            text = node.id
        elif isinstance(node, ast.Attribute):
            text = node.attr
        if text is not None and any(t in text.lower() for t in _TMPISH):
            return True
    return False


def _is_thread_ctor(call):
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == "Thread"
    return isinstance(f, ast.Attribute) and f.attr == "Thread"


def _daemon_safe(call):
    """True when the constructor itself settles the question: an explicit
    ``daemon=True``, or a non-literal ``daemon=`` expression the pass gives
    the benefit of the doubt."""
    for kw in call.keywords:
        if kw.arg == "daemon":
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return True
    return False


def _assign_target(parents, call):
    """The storage target string (``self._thread``, ``t``) when the Thread
    call is the whole right-hand side of a simple assignment, else None."""
    node, parent = call, parents.get(call)
    while parent is not None and not isinstance(parent, ast.stmt):
        node, parent = parent, parents.get(parent)
    if (isinstance(parent, ast.Assign) and parent.value is node
            and len(parent.targets) == 1
            and isinstance(parent.targets[0], (ast.Name, ast.Attribute))):
        return ast.unparse(parent.targets[0])
    return None


def _owner_scope(parents, call, target):
    """Where a matching join() may legitimately live: the enclosing class
    for ``self.*`` targets (shutdown lives in a sibling method), else the
    enclosing function, else the module."""
    want_class = target is not None and target.startswith("self.")
    node = parents.get(call)
    fallback = None
    while node is not None:
        if want_class and isinstance(node, ast.ClassDef):
            return node
        if not want_class and isinstance(node,
                                         (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
            return node
        if isinstance(node, ast.Module):
            fallback = node
        node = parents.get(node)
    return fallback


def _target_released(scope, target):
    """True when ``target`` is joined (``target.join(...)``) or daemonized
    after the fact (``target.daemon = True``) anywhere in ``scope``."""
    for node in ast.walk(scope):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and ast.unparse(node.func.value) == target):
            return True
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and node.targets[0].attr == "daemon"
                and ast.unparse(node.targets[0].value) == target
                and isinstance(node.value, ast.Constant)
                and bool(node.value.value)):
            return True
    return False


@register_pass
class RobustnessPass(AnalysisPass):
    name = "robustness"
    version = 5
    codes = ("RB101", "RB102", "RB103", "RB104", "RB105")
    description = ("swallowed exceptions: broad except handlers whose "
                   "whole body is pass (RB101) or a bare "
                   "continue/break/return (RB102); orphan threads: "
                   "non-daemon Thread never joined (RB103); hand-rolled "
                   "retry loops sleeping through time.sleep instead of "
                   "core.retry (RB104); create-truncate writes to final "
                   "paths in modules that elsewhere follow the atomic "
                   "write-rename(+fsync) idiom (RB105)")

    def check_file(self, src) -> list[Finding]:
        findings: list[Finding] = []
        parents = {}
        for node in ast.walk(src.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        persistence = _persistence_module(src.tree)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ExceptHandler):
                findings.extend(self._check_handler(src, node))
            elif isinstance(node, ast.Call) and _is_thread_ctor(node):
                findings.extend(self._check_thread(src, node, parents))
            elif (persistence and isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "open"):
                findings.extend(self._check_atomic_write(src, node))
            elif isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
                findings.extend(self._check_retry_loop(src, node))
        return findings

    def _check_atomic_write(self, src, call):
        mode = _open_truncates(call)
        if mode is None or _tmpish_path(call):
            return []
        return [Finding(
            self.name, "RB105", src.path, call.lineno,
            f"open(..., {mode!r}) to a final path in a persistence module "
            f"— a crash mid-write leaves a torn file where the module's "
            f"own os.replace idiom would not",
            _ATOMIC_HINT, severity="warning")]

    def _check_handler(self, src, node):
        if not _is_broad(node):
            return []
        what = ("bare except" if node.type is None
                else f"except {ast.unparse(node.type)}")
        if _swallows(node):
            return [Finding(
                self.name, "RB101", src.path, node.lineno,
                f"{what}: pass — swallows every failure silently",
                _HINT, severity="warning")]
        esc = _escapes(node)
        if esc:
            return [Finding(
                self.name, "RB102", src.path, node.lineno,
                f"{what}: {esc} — swallows the failure and silently "
                f"drops the iteration's work",
                _HINT, severity="warning")]
        return []

    def _check_retry_loop(self, src, loop):
        kind = "while" if isinstance(loop, ast.While) else "for"
        return [Finding(
            self.name, "RB104", src.path, call.lineno,
            f"bare time.sleep inside a {kind} retry loop — flat, "
            f"unjittered, uncounted backoff bypassing core.retry's "
            f"RetryPolicy",
            _RETRY_HINT, severity="warning")
            for call in _retry_sleeps(loop)]

    def _check_thread(self, src, call, parents):
        if _daemon_safe(call):
            return []
        target = _assign_target(parents, call)
        if target is not None:
            scope = _owner_scope(parents, call, target)
            if scope is not None and _target_released(scope, target):
                return []
        what = (f"thread stored in {target!r}" if target is not None
                else "anonymous thread")
        return [Finding(
            self.name, "RB103", src.path, call.lineno,
            f"non-daemon Thread without a matching join(): {what} "
            f"outlives its owner and blocks interpreter exit",
            _THREAD_HINT, severity="warning")]
