"""robustness — swallowed-exception hygiene.

A broad handler whose whole body is ``pass`` discards every failure — the
archetypal fault-tolerance anti-pattern this PR's serving work is built to
avoid (quarantine records the error on the request; the watchdog counts its
expiries; the retry helper re-raises after backoff).  Flagged:

  * ``except: pass`` / ``except Exception: pass`` /
    ``except BaseException: pass`` (``...`` counts as ``pass``).  (RB101)
  * a broad handler whose whole body is a bare control-flow escape —
    ``continue``, ``break``, ``return`` / ``return None`` — the loop-shaped
    variant of the same swallow: the failure vanishes AND the iteration's
    work silently disappears with it.  (RB102)

Narrow handlers (``except KeyError: continue``) are idiomatic probing and
stay silent, as are broad handlers that do anything observable (log, count,
record) before escaping.  Deliberate broad swallows — shutdown paths where
any cleanup error is acceptable, best-effort per-item scans — carry a line
pragma or a baseline entry stating so.
"""
from __future__ import annotations

import ast

from ..framework import AnalysisPass, Finding, register_pass

_HINT = ("handle the error, re-raise, or log it (module logger / "
         "observability registry); a deliberate swallow names the narrow "
         "exception it expects or carries a pragma")

_BROAD = ("Exception", "BaseException")


def _is_broad(handler):
    t = handler.type
    if t is None:                                        # bare except:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _swallows(handler):
    if len(handler.body) != 1:
        return False
    stmt = handler.body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


def _escapes(handler):
    """Body is a single bare control-flow escape: the RB102 shape.  A
    ``return <value>`` (other than an explicit None) communicates something
    to the caller, so it does not count."""
    if len(handler.body) != 1:
        return False
    stmt = handler.body[0]
    if isinstance(stmt, (ast.Continue, ast.Break)):
        return type(stmt).__name__.lower()
    if isinstance(stmt, ast.Return):
        if stmt.value is None or (isinstance(stmt.value, ast.Constant)
                                  and stmt.value.value is None):
            return "return"
    return False


@register_pass
class RobustnessPass(AnalysisPass):
    name = "robustness"
    version = 2
    description = ("swallowed exceptions: broad except handlers whose "
                   "whole body is pass (RB101) or a bare "
                   "continue/break/return (RB102)")

    def check_file(self, src) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            what = ("bare except" if node.type is None
                    else f"except {ast.unparse(node.type)}")
            if _swallows(node):
                findings.append(Finding(
                    self.name, "RB101", src.path, node.lineno,
                    f"{what}: pass — swallows every failure silently",
                    _HINT, severity="warning"))
                continue
            esc = _escapes(node)
            if esc:
                findings.append(Finding(
                    self.name, "RB102", src.path, node.lineno,
                    f"{what}: {esc} — swallows the failure and silently "
                    f"drops the iteration's work",
                    _HINT, severity="warning"))
        return findings
