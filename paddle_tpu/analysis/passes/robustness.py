"""robustness — swallowed-exception and orphan-thread hygiene.

A broad handler whose whole body is ``pass`` discards every failure — the
archetypal fault-tolerance anti-pattern this PR's serving work is built to
avoid (quarantine records the error on the request; the watchdog counts its
expiries; the retry helper re-raises after backoff).  Flagged:

  * ``except: pass`` / ``except Exception: pass`` /
    ``except BaseException: pass`` (``...`` counts as ``pass``).  (RB101)
  * a broad handler whose whole body is a bare control-flow escape —
    ``continue``, ``break``, ``return`` / ``return None`` — the loop-shaped
    variant of the same swallow: the failure vanishes AND the iteration's
    work silently disappears with it.  (RB102)
  * a non-daemon ``threading.Thread(...)`` that is never ``join()``ed (nor
    later marked daemon): library code that starts one leaks a thread that
    blocks interpreter exit and outlives every ``close()``.  The fleet's
    worker/supervisor/heartbeat threads are the motivating consumers: each
    is ``daemon=True`` AND joined on its shutdown path.  (RB103)

Narrow handlers (``except KeyError: continue``) are idiomatic probing and
stay silent, as are broad handlers that do anything observable (log, count,
record) before escaping.  A thread constructed with ``daemon=True`` (or a
non-literal ``daemon=`` the pass can't evaluate) passes RB103, as does any
thread whose storage target is joined somewhere in its enclosing class or
function.  Deliberate exceptions carry a line pragma or a baseline entry.
"""
from __future__ import annotations

import ast

from ..framework import AnalysisPass, Finding, register_pass

_HINT = ("handle the error, re-raise, or log it (module logger / "
         "observability registry); a deliberate swallow names the narrow "
         "exception it expects or carries a pragma")

_THREAD_HINT = ("pass daemon=True at construction, or join() the thread on "
                "the owner's shutdown path (close/stop); do both for "
                "threads that must not outlive their owner")

_BROAD = ("Exception", "BaseException")


def _is_broad(handler):
    t = handler.type
    if t is None:                                        # bare except:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _swallows(handler):
    if len(handler.body) != 1:
        return False
    stmt = handler.body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


def _escapes(handler):
    """Body is a single bare control-flow escape: the RB102 shape.  A
    ``return <value>`` (other than an explicit None) communicates something
    to the caller, so it does not count."""
    if len(handler.body) != 1:
        return False
    stmt = handler.body[0]
    if isinstance(stmt, (ast.Continue, ast.Break)):
        return type(stmt).__name__.lower()
    if isinstance(stmt, ast.Return):
        if stmt.value is None or (isinstance(stmt.value, ast.Constant)
                                  and stmt.value.value is None):
            return "return"
    return False


def _is_thread_ctor(call):
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == "Thread"
    return isinstance(f, ast.Attribute) and f.attr == "Thread"


def _daemon_safe(call):
    """True when the constructor itself settles the question: an explicit
    ``daemon=True``, or a non-literal ``daemon=`` expression the pass gives
    the benefit of the doubt."""
    for kw in call.keywords:
        if kw.arg == "daemon":
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return True
    return False


def _assign_target(parents, call):
    """The storage target string (``self._thread``, ``t``) when the Thread
    call is the whole right-hand side of a simple assignment, else None."""
    node, parent = call, parents.get(call)
    while parent is not None and not isinstance(parent, ast.stmt):
        node, parent = parent, parents.get(parent)
    if (isinstance(parent, ast.Assign) and parent.value is node
            and len(parent.targets) == 1
            and isinstance(parent.targets[0], (ast.Name, ast.Attribute))):
        return ast.unparse(parent.targets[0])
    return None


def _owner_scope(parents, call, target):
    """Where a matching join() may legitimately live: the enclosing class
    for ``self.*`` targets (shutdown lives in a sibling method), else the
    enclosing function, else the module."""
    want_class = target is not None and target.startswith("self.")
    node = parents.get(call)
    fallback = None
    while node is not None:
        if want_class and isinstance(node, ast.ClassDef):
            return node
        if not want_class and isinstance(node,
                                         (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
            return node
        if isinstance(node, ast.Module):
            fallback = node
        node = parents.get(node)
    return fallback


def _target_released(scope, target):
    """True when ``target`` is joined (``target.join(...)``) or daemonized
    after the fact (``target.daemon = True``) anywhere in ``scope``."""
    for node in ast.walk(scope):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and ast.unparse(node.func.value) == target):
            return True
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and node.targets[0].attr == "daemon"
                and ast.unparse(node.targets[0].value) == target
                and isinstance(node.value, ast.Constant)
                and bool(node.value.value)):
            return True
    return False


@register_pass
class RobustnessPass(AnalysisPass):
    name = "robustness"
    version = 3
    description = ("swallowed exceptions: broad except handlers whose "
                   "whole body is pass (RB101) or a bare "
                   "continue/break/return (RB102); orphan threads: "
                   "non-daemon Thread never joined (RB103)")

    def check_file(self, src) -> list[Finding]:
        findings: list[Finding] = []
        parents = {}
        for node in ast.walk(src.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ExceptHandler):
                findings.extend(self._check_handler(src, node))
            elif isinstance(node, ast.Call) and _is_thread_ctor(node):
                findings.extend(self._check_thread(src, node, parents))
        return findings

    def _check_handler(self, src, node):
        if not _is_broad(node):
            return []
        what = ("bare except" if node.type is None
                else f"except {ast.unparse(node.type)}")
        if _swallows(node):
            return [Finding(
                self.name, "RB101", src.path, node.lineno,
                f"{what}: pass — swallows every failure silently",
                _HINT, severity="warning")]
        esc = _escapes(node)
        if esc:
            return [Finding(
                self.name, "RB102", src.path, node.lineno,
                f"{what}: {esc} — swallows the failure and silently "
                f"drops the iteration's work",
                _HINT, severity="warning")]
        return []

    def _check_thread(self, src, call, parents):
        if _daemon_safe(call):
            return []
        target = _assign_target(parents, call)
        if target is not None:
            scope = _owner_scope(parents, call, target)
            if scope is not None and _target_released(scope, target):
                return []
        what = (f"thread stored in {target!r}" if target is not None
                else "anonymous thread")
        return [Finding(
            self.name, "RB103", src.path, call.lineno,
            f"non-daemon Thread without a matching join(): {what} "
            f"outlives its owner and blocks interpreter exit",
            _THREAD_HINT, severity="warning")]
