"""robustness — swallowed-exception hygiene.

A broad handler whose whole body is ``pass`` discards every failure — the
archetypal fault-tolerance anti-pattern this PR's serving work is built to
avoid (quarantine records the error on the request; the watchdog counts its
expiries; the retry helper re-raises after backoff).  Flagged:

  * ``except: pass`` / ``except Exception: pass`` /
    ``except BaseException: pass`` (``...`` counts as ``pass``).  (RB101)

Narrow handlers (``except KeyError: pass``) are idiomatic dict-probing and
stay silent.  Deliberate broad swallows — shutdown paths where any cleanup
error is acceptable — carry a line pragma or a baseline entry stating so.
"""
from __future__ import annotations

import ast

from ..framework import AnalysisPass, Finding, register_pass

_HINT = ("handle the error, re-raise, or log it (module logger / "
         "observability registry); a deliberate swallow names the narrow "
         "exception it expects or carries a pragma")

_BROAD = ("Exception", "BaseException")


def _is_broad(handler):
    t = handler.type
    if t is None:                                        # bare except:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _swallows(handler):
    if len(handler.body) != 1:
        return False
    stmt = handler.body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


@register_pass
class RobustnessPass(AnalysisPass):
    name = "robustness"
    version = 1
    description = ("swallowed exceptions: broad except handlers whose "
                   "whole body is pass")

    def check_file(self, src) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node) and _swallows(node):
                what = ("bare except" if node.type is None
                        else f"except {ast.unparse(node.type)}")
                findings.append(Finding(
                    self.name, "RB101", src.path, node.lineno,
                    f"{what}: pass — swallows every failure silently",
                    _HINT, severity="warning"))
        return findings
