"""namespace-parity — the static analog of ``tests/test_namespace_parity.py``.

Every name a module declares in ``__all__`` must actually exist on the
module; a stale export breaks ``from paddle_tpu.x import *`` users and the
reference-parity sweep, and nothing else catches it until an import happens
to touch the missing attribute.

For files inside an importable package, ground truth is the imported module's
attribute set.  For loose files (fixtures, scripts) a static approximation is
used: top-level defs, classes, assignments and import aliases — unless a
``from x import *`` makes the static view unsound, in which case the file is
skipped rather than guessed at.

  * NS001 name declared in ``__all__`` but absent from the module
  * NS002 duplicate name inside ``__all__``
"""
from __future__ import annotations

import ast
import importlib

from ..framework import AnalysisPass, Finding, Project, register_pass


def _all_decls(tree):
    """[(line, [names...])] for ``__all__ = [...]`` and ``__all__ += [...]``;
    non-literal constructions return names=None (unknowable)."""
    decls = []
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
        elif isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Name):
            target = node.target.id
        if target != "__all__":
            continue
        value = node.value
        if isinstance(value, (ast.List, ast.Tuple, ast.Set)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in value.elts):
            decls.append((node.lineno, [e.value for e in value.elts]))
        else:
            decls.append((node.lineno, None))
    return decls


def _static_names(tree):
    """(names defined at module top level, sound: bool).  A star import makes
    the static view unsound."""
    names, sound = set(), True
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(node.target, ast.Name):
                names.add(node.target.id)
        elif isinstance(node, ast.Import):
            for a in node.names:
                names.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "*":
                    sound = False
                else:
                    names.add(a.asname or a.name)
        elif isinstance(node, (ast.If, ast.Try)):
            # common guarded-import shape: collect from all branches
            sub = ast.Module(body=list(ast.iter_child_nodes(node)),
                             type_ignores=[])
            inner, inner_sound = _static_names(sub)
            names |= inner
            sound &= inner_sound
    return names, sound


@register_pass
class NamespaceParityPass(AnalysisPass):
    name = "namespace-parity"
    version = 1
    codes = ("NS001", "NS002")
    description = "__all__ entries must resolve to real module attributes"
    project_scope = True    # imports modules for ground truth

    def check_project(self, project: Project) -> list[Finding]:
        findings = []
        for src in project.files:
            decls = _all_decls(src.tree)
            if not any(names for _, names in decls):
                continue
            have, sound = self._module_names(src)
            for line, names in decls:
                if names is None:
                    continue
                seen = set()
                for n in names:
                    if n in seen:
                        findings.append(Finding(
                            self.name, "NS002", src.path, line,
                            f"'{n}' listed twice in __all__",
                            hint="drop the duplicate"))
                    seen.add(n)
                    if sound and have is not None and n not in have:
                        findings.append(Finding(
                            self.name, "NS001", src.path, line,
                            f"__all__ exports '{n}' but the module has no "
                            "such attribute",
                            hint="define/import the name or remove the "
                                 "stale export"))
        return findings

    @staticmethod
    def _module_names(src):
        mod_name = Project.module_name(src.path)
        if mod_name is not None:
            try:
                mod = importlib.import_module(mod_name)
                return set(dir(mod)), True
            except Exception:
                pass            # fall back to the static view
        return _static_names(src.tree)
