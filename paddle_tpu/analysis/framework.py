"""graftlint core — pass registry, source model, pragma suppression.

The reference repo spends ~19k LoC of ``tools/`` on CI linters that keep the
declarative op table, the generated API surface, and the exported namespaces
consistent.  This is the same idea for the jax_graft reproduction: a small
AST-based framework whose passes catch the bug classes unit tests can't —
registry drift, stale ``__all__`` exports, and JAX trace-unsafe idioms that
silently recompile or leak tracers.

Pass contract: subclass :class:`AnalysisPass` and register with
:func:`register_pass`.  A pass implements one of

  * ``check_file(source_file) -> list[Finding]``   (per-file; cacheable),
  * ``check_project(project) -> list[Finding]``    (whole-tree; never cached),
  * ``check_summaries(source_file, index) -> list[Finding]``
    (``summary_scope``: per-file findings against the whole-program
    :class:`~.summaries.SummaryIndex`; cacheable with cross-file dep
    digests so editing a fact-contributing module re-lints its dependents)

Suppression pragmas (the clang-tidy ``NOLINT`` analog):

  * ``# graftlint: disable=<pass>[,<pass>...]``       on the flagged line
  * ``# graftlint: disable-file=<pass>[,<pass>...]``  anywhere in the file
  * ``all`` is accepted as a pass name in both forms.
"""
from __future__ import annotations

import ast
import hashlib
import os
import re
import sys
from dataclasses import dataclass, field


SEVERITIES = ("error", "warning")


@dataclass(frozen=True)
class Finding:
    """One diagnostic: a pass, a location, a short code, and a fix hint.

    ``severity`` is ``"error"`` (breaks CI / exit code 1) or ``"warning"``
    (reported, baselineable, non-fatal under the default ``--fail-on error``).
    """
    pass_name: str
    code: str
    path: str
    line: int
    message: str
    hint: str = ""
    severity: str = "error"

    def to_dict(self):
        return {"pass": self.pass_name, "code": self.code, "path": self.path,
                "line": self.line, "message": self.message, "hint": self.hint,
                "severity": self.severity}

    @classmethod
    def from_dict(cls, d):
        return cls(d["pass"], d["code"], d["path"], d["line"], d["message"],
                   d.get("hint", ""), d.get("severity", "error"))

    def render(self):
        sev = "" if self.severity == "error" else f" {self.severity}:"
        tail = f"  [fix: {self.hint}]" if self.hint else ""
        return (f"{self.path}:{self.line}:{sev} {self.code} "
                f"[{self.pass_name}] {self.message}{tail}")

    def fingerprint(self) -> str:
        """Stable identity for baselining: pass, code, repo-relative path and
        message — deliberately NOT the line number, so unrelated edits above
        a baselined finding don't resurrect it."""
        key = "|".join((self.pass_name, self.code, norm_path(self.path),
                        self.message))
        return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]


def norm_path(path: str) -> str:
    """Machine-independent spelling of ``path`` for fingerprints: the
    project-relative tail starting at the first package component
    (``paddle_tpu``/``tests``/``examples``), else the basename."""
    parts = path.replace(os.sep, "/").split("/")
    for marker in ("paddle_tpu", "tests", "examples"):
        if marker in parts:
            return "/".join(parts[parts.index(marker):])
    return parts[-1]


_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\- ]+)")


class SourceFile:
    """A parsed python file plus its suppression pragmas."""

    def __init__(self, path: str, text: str | None = None):
        self.path = path
        if text is None:
            with open(path, encoding="utf-8", errors="replace") as f:
                text = f.read()
        self.text = text
        self.syntax_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:            # surfaced as a framework finding
            self.tree = ast.Module(body=[], type_ignores=[])
            self.syntax_error = e
        # line -> set of disabled pass names; "all" disables every pass
        self.line_pragmas: dict[int, set[str]] = {}
        self.file_pragmas: set[str] = set()
        for i, line in enumerate(text.splitlines(), start=1):
            m = _PRAGMA_RE.search(line)
            if not m:
                continue
            names = {n.strip() for n in m.group(2).split(",") if n.strip()}
            if m.group(1) == "disable-file":
                self.file_pragmas |= names
            else:
                self.line_pragmas.setdefault(i, set()).update(names)

    def suppressed(self, finding: Finding) -> bool:
        if {"all", finding.pass_name} & self.file_pragmas:
            return True
        on_line = self.line_pragmas.get(finding.line, ())
        return bool({"all", finding.pass_name} & set(on_line))


class Project:
    """The analyzed file set with module-name resolution."""

    def __init__(self, files: list[SourceFile]):
        self.files = files
        self.by_path = {f.path: f for f in files}

    @staticmethod
    def module_name(path: str) -> str | None:
        """Dotted module name for ``path`` if it sits inside an importable
        package chain (``__init__.py`` all the way up to a ``sys.path``
        root); None for loose scripts and test fixtures."""
        path = os.path.abspath(path)
        d, base = os.path.split(path)
        parts = [] if base == "__init__.py" else [base[:-3]]
        while os.path.isfile(os.path.join(d, "__init__.py")):
            d, pkg = os.path.split(d)
            parts.insert(0, pkg)
        if not parts:
            return None
        root_ok = any(os.path.abspath(p or ".") == d for p in sys.path)
        return ".".join(parts) if root_ok else None


class AnalysisPass:
    """Base class: set ``name`` (kebab-case, the pragma key), bump ``version``
    whenever the pass's rules change (invalidates per-file cache entries)."""

    name: str = ""
    version: int = 1
    description: str = ""
    codes: tuple = ()             # rule IDs the pass can emit (CLI listing)
    rule_docs: dict = {}          # code -> explanation (CLI --explain)
    rule_severities: dict = {}    # code -> severity note (CLI --explain)
    project_scope: bool = False   # True -> check_project, uncacheable
    summary_scope: bool = False   # True -> check_summaries, dep-cached
    summary_domains: tuple = ()   # SummaryIndex fact domains consulted

    def check_file(self, src: SourceFile) -> list[Finding]:
        return []

    def check_project(self, project: Project) -> list[Finding]:
        return []

    def check_summaries(self, src: SourceFile, index) -> list[Finding]:
        return []


PASSES: dict[str, AnalysisPass] = {}


def register_pass(cls):
    """Class decorator: instantiate and add to the pass registry."""
    inst = cls()
    assert inst.name and inst.name not in PASSES, f"bad pass {cls}"
    PASSES[inst.name] = inst
    return cls


def iter_python_files(paths):
    """Expand files/dirs into .py paths, skipping caches and hidden dirs."""
    skip = {"__pycache__", "build", "dist", ".git"}
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in skip and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


@dataclass
class RunResult:
    findings: list[Finding] = field(default_factory=list)
    files: int = 0
    passes: list[str] = field(default_factory=list)
    suppressed: int = 0
    cache_hits: int = 0
    baselined: int = 0

    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "error"]


def run(paths, select=None, disable=None, cache=None,
        baseline=None) -> RunResult:
    """Run the selected passes over ``paths``; returns findings with
    pragma-suppressed ones dropped (counted in ``suppressed``).  ``baseline``
    is an optional :class:`~paddle_tpu.analysis.baseline.Baseline`: findings
    it already records are dropped too (counted in ``baselined``)."""
    # load pass modules lazily so `import paddle_tpu` never pays for them
    from . import passes as _passes  # noqa: F401  (registration side effect)
    names = sorted(PASSES) if not select else list(select)
    for n in names:
        if n not in PASSES:
            raise KeyError(f"unknown pass {n!r} (have: {', '.join(sorted(PASSES))})")
    if disable:
        names = [n for n in names if n not in set(disable)]
    files = [SourceFile(p) for p in iter_python_files(paths)]
    project = Project(files)
    result = RunResult(files=len(files), passes=names)
    raw: list[Finding] = []
    for f in files:
        if f.syntax_error is not None:
            raw.append(Finding("framework", "GL000", f.path,
                               f.syntax_error.lineno or 1,
                               f"syntax error: {f.syntax_error.msg}"))
    index = None
    if any(PASSES[n].summary_scope for n in names):
        from .summaries import SummaryIndex
        index = SummaryIndex(project, cache=cache)
    for n in names:
        p = PASSES[n]
        if p.project_scope:
            raw.extend(p.check_project(project))
            continue
        deps = index.pass_deps(p) if p.summary_scope else None
        for f in files:
            cached = cache.get(f, p, deps=deps) if cache is not None else None
            if cached is not None:
                result.cache_hits += 1
                raw.extend(cached)
                continue
            found = p.check_summaries(f, index) if p.summary_scope \
                else p.check_file(f)
            if cache is not None:
                cache.put(f, p, found, deps=deps)
            raw.extend(found)
    for fd in raw:
        src = project.by_path.get(fd.path)
        if src is not None and src.suppressed(fd):
            result.suppressed += 1
        elif baseline is not None and fd in baseline:
            result.baselined += 1
        else:
            result.findings.append(fd)
    result.findings.sort(key=lambda x: (x.path, x.line, x.code))
    if cache is not None:
        cache.save()
    return result
