"""graftlint — trace-safety and registry-parity static analysis.

The reference repo's ~19k LoC of ``tools/`` CI linters, reimagined for the
jax_graft reproduction: AST passes that catch registry drift, stale
``__all__`` exports, and JAX trace-unsafe idioms (the silent-recompile /
tracer-leak bug class) without running any device code.

Usage::

    python -m paddle_tpu.analysis paddle_tpu/ [--format json]
    graftlint paddle_tpu/ --select trace-safety,registry-parity

Programmatic::

    from paddle_tpu.analysis import run
    result = run(["paddle_tpu/"])
    assert not result.findings

Pass modules live in :mod:`paddle_tpu.analysis.passes`; new passes register
with :func:`register_pass` and are picked up by the CLI automatically.
"""
from .framework import (AnalysisPass, Finding, PASSES, Project,  # noqa: F401
                        RunResult, SourceFile, register_pass, run)

__all__ = ["AnalysisPass", "Finding", "PASSES", "Project", "RunResult",
           "SourceFile", "register_pass", "run"]
