"""Automatic Structured Pruning (2:4 sparsity) — reference:
python/paddle/incubate/asp/asp.py (set_excluded_layers:55, decorate:233,
prune_model:319) and utils.py (mask generation / density).

TPU-native realization: the mask IS the mechanism. The reference prunes so
CUDA sparse-tensor-core kernels can exploit 2:4 patterns; on TPU there is no
sparse MXU path, so ASP's value is model-compression workflows (train sparse,
export). Masks are jnp 0/1 arrays held in a registry; `decorate` wraps
`optimizer.step` to re-apply masks after each update, preserving the sparsity
invariant exactly like the reference's OptimizerWithSparsityGuarantee.
"""
from __future__ import annotations

import weakref

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density",
           "check_sparsity", "create_mask", "clear_masks"]

# id(param) -> (weakref(param), mask); weakrefs so a discarded pruned model
# is collectable — dead entries are purged on access
_masks: dict[int, tuple] = {}
_excluded: set[str] = set()


def _live_masks():
    dead = [k for k, (ref, _) in _masks.items() if ref() is None]
    for k in dead:
        del _masks[k]
    return _masks


def clear_masks():
    """Drop every registered sparsity mask (masks also vanish automatically
    when the pruned parameters are garbage-collected)."""
    _masks.clear()


def set_excluded_layers(layers=None, main_program=None, param_names=None):
    """Exclude sublayers (by name) from pruning (reference asp.py:55; the
    static-graph main_program form is accepted and ignored — there is no
    separate static program here)."""
    names = param_names if param_names is not None else layers
    if names:
        _excluded.update(names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def calculate_density(x) -> float:
    """Fraction of nonzeros (reference utils.py calculate_density)."""
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(1, arr.size)


def _mask_1d(w: np.ndarray, n: int, m: int) -> np.ndarray:
    """n:m along the last axis: in every group of m consecutive elements keep
    the n largest |w| (reference utils.py get_mask_1d)."""
    flat = w.reshape(-1, m)
    order = np.argsort(-np.abs(flat), axis=1)[:, :n]
    mask = np.zeros_like(flat, dtype=w.dtype)
    np.put_along_axis(mask, order, 1.0, axis=1)
    return mask.reshape(w.shape)


def _mask_2d_greedy(w: np.ndarray, n: int, m: int) -> np.ndarray:
    """2-D n:m over m x m patches: greedily keep the largest-|w| entries
    subject to per-row AND per-column budgets of n inside each patch — both
    directions satisfy n:m exactly (reference utils.py get_mask_2d_greedy).
    Requires both trailing dims divisible by m."""
    mat = w.reshape(-1, w.shape[-1])
    R, C = mat.shape
    if R % m or C % m:
        raise ValueError(
            f"mask_2d needs both matrix dims divisible by {m}, got {mat.shape}")
    # [P, m, m] patches
    patches = np.abs(mat).reshape(R // m, m, C // m, m).transpose(0, 2, 1, 3)
    P = patches.reshape(-1, m, m)
    order = np.argsort(-P.reshape(len(P), m * m), axis=1)
    mask = np.zeros((len(P), m, m), w.dtype)
    rowc = np.zeros((len(P), m), np.int64)
    colc = np.zeros((len(P), m), np.int64)
    ar = np.arange(len(P))
    for k in range(m * m):
        e = order[:, k]
        r, c = e // m, e % m
        ok = (rowc[ar, r] < n) & (colc[ar, c] < n)
        mask[ar, r, c] = np.where(ok, 1.0, mask[ar, r, c])
        rowc[ar, r] += ok
        colc[ar, c] += ok
    out = mask.reshape(R // m, C // m, m, m).transpose(0, 2, 1, 3)
    return out.reshape(w.shape)


_best_patterns: dict = {}


def _patterns_2d(n: int, m: int) -> np.ndarray:
    """All m x m 0/1 patterns with every row AND column summing to exactly n
    (for 2:4 that's 90 patterns), flattened to [P, m*m]. Cached per (n, m)."""
    import itertools
    import math as _math
    key = (n, m)
    # the search space is C(m,n)^m row combinations — fine for the canonical
    # 2:4 (1296 -> 90 valid), intractable beyond; refuse rather than hang
    if _math.comb(m, n) ** m > 200_000:
        raise ValueError(
            f"mask_2d_best is exhaustive and infeasible for n={n}, m={m} "
            f"(C({m},{n})^{m} candidates); use mask_2d_greedy")
    if key not in _best_patterns:
        rows = [np.bincount(c, minlength=m)
                for c in itertools.combinations(range(m), n)]
        pats = []
        for combo in itertools.product(rows, repeat=m):
            grid = np.stack(combo)
            if (grid.sum(axis=0) == n).all():
                pats.append(grid.reshape(-1))
        _best_patterns[key] = np.stack(pats).astype(np.float32)
    return _best_patterns[key]


def _mask_2d_best(w: np.ndarray, n: int, m: int) -> np.ndarray:
    """Exhaustive per-patch optimum over all exactly-n:m-both-ways patterns
    (reference utils.py get_mask_2d_best): for each m x m patch pick the
    pattern maximizing the kept |w| sum. Vectorized: one [P_patterns, m*m] x
    [m*m, n_patches] matmul + argmax."""
    mat = w.reshape(-1, w.shape[-1])
    R, C = mat.shape
    if R % m or C % m:
        raise ValueError(
            f"mask_2d needs both matrix dims divisible by {m}, got {mat.shape}")
    pats = _patterns_2d(n, m)                                  # [P, m*m]
    patches = np.abs(mat).reshape(R // m, m, C // m, m).transpose(0, 2, 1, 3)
    flat = patches.reshape(-1, m * m).astype(np.float32)       # [N, m*m]
    best = np.argmax(pats @ flat.T, axis=0)                    # [N]
    mask = pats[best].reshape(R // m, C // m, m, m).transpose(0, 2, 1, 3)
    return mask.reshape(w.shape).astype(w.dtype)


_MASK_ALGOS = {"mask_1d": _mask_1d, "mask_2d_greedy": _mask_2d_greedy,
               "mask_2d_best": _mask_2d_best}


def create_mask(w, n=2, m=4, mask_algo="mask_1d") -> np.ndarray:
    arr = np.asarray(w._data if isinstance(w, Tensor) else w, np.float32)
    if arr.ndim < 2 or arr.shape[-1] % m != 0:
        raise ValueError(
            f"cannot {n}:{m}-prune shape {arr.shape}: need ndim>=2 and last "
            f"dim divisible by {m}")
    try:
        fn = _MASK_ALGOS[mask_algo]
    except KeyError:
        raise ValueError(f"unknown mask_algo {mask_algo!r}; "
                         f"one of {sorted(_MASK_ALGOS)}")
    return fn(arr, n, m)


def check_sparsity(x, n=2, m=4) -> bool:
    """True when every m-group ALONG THE LAST AXIS has at most n nonzeros
    (reference utils.py check_mask_1d semantics). Groups never straddle rows;
    shapes whose last dim isn't divisible by m are simply not n:m-sparse."""
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    if arr.ndim == 0:
        return False
    if arr.ndim > 2:
        arr = arr.reshape(arr.shape[0], -1)   # conv view, matching prune_model
    if arr.shape[-1] % m != 0:
        return False
    rows = (arr != 0).reshape(-1, arr.shape[-1])
    groups = rows.reshape(rows.shape[0], -1, m)
    return bool((groups.sum(axis=2) <= n).all())


def _prunable(name, layer):
    w = getattr(layer, "weight", None)
    if w is None or w.ndim < 2:
        return None
    if name in _excluded or type(layer).__name__ in _excluded:
        return None
    return w


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Prune every supported sublayer's weight to n:m sparsity and (when
    with_mask) register the mask so `decorate`d optimizers re-apply it after
    each step (reference asp.py:319 prune_model)."""
    pruned = {}
    for name, layer in model.named_sublayers(include_self=False):
        w = _prunable(name, layer)
        if w is None:
            continue
        arr = np.asarray(w._buf, np.float32)
        if arr.ndim > 2:
            # conv [out, in, kh, kw] -> [out, in*kh*kw]: n:m along the
            # flattened reduction dim (reference supported_layer_list
            # reshapes conv weights the same way; depthwise convs whose
            # flattened dim isn't divisible are skipped)
            flat = arr.reshape(arr.shape[0], -1)
        else:
            flat = arr
        if flat.ndim < 2 or flat.shape[-1] % m != 0 or \
                (mask_algo != "mask_1d" and flat.shape[0] % m != 0):
            continue
        mask = create_mask(flat, n, m, mask_algo).reshape(arr.shape)
        mask = jnp.asarray(mask, w._buf.dtype)
        w._data = w._buf * mask
        if with_mask:
            _masks[id(w)] = (weakref.ref(w), mask)
        pruned[name] = float(mask.mean())
    return pruned


class OptimizerWithSparsityGuarantee:
    """Re-applies registered masks after every step (reference asp.py: the
    decorated optimizer masks grads/params so pruned weights stay pruned).
    Only THIS optimizer's parameters are touched — two decorated optimizers
    over different models don't cross-couple."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def step(self):
        self._optimizer.step()
        own = {id(p) for p in self._optimizer._parameter_list}
        for pid, (ref, mask) in list(_live_masks().items()):
            p = ref()
            if p is not None and pid in own:
                p._data = p._buf * mask

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


def decorate(optimizer) -> OptimizerWithSparsityGuarantee:
    return OptimizerWithSparsityGuarantee(optimizer)
