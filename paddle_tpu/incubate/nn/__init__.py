"""incubate.nn fused layers: on TPU, 'fused' == XLA-fused; these re-export the
standard layers whose dispatch already fuses under jit (SURVEY §2.1 fused ops)."""
from ...nn.layer.transformer import MultiHeadAttention as FusedMultiHeadAttention  # noqa: F401
from ...nn.layer.transformer import TransformerEncoderLayer as FusedTransformerEncoderLayer  # noqa: F401
