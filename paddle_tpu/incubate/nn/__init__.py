"""incubate.nn fused layers (reference: python/paddle/incubate/nn —
FusedMultiHeadAttention/layer.py, FusedFeedForward, FusedTransformerEncoderLayer,
FusedLinear).

TPU-native: each layer drives the fused functional ops (one dispatched body
per block; attention rides the flash kernel) instead of aliasing the unfused
layers."""
from __future__ import annotations

import numpy as np

from ...nn.layer.layers import Layer
from ...nn.initializer import XavierUniform, Constant
from . import functional as incubate_F

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedLinear"]


class FusedLinear(Layer):
    """reference: incubate/nn/layer/fused_linear.py."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        shape = [out_features, in_features] if transpose_weight else \
            [in_features, out_features]
        self.weight = self.create_parameter(
            shape, attr=weight_attr, default_initializer=XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self._transpose = transpose_weight

    def forward(self, x):
        return incubate_F.fused_linear(x, self.weight, self.bias,
                                       self._transpose)


class FusedMultiHeadAttention(Layer):
    """reference: incubate/nn/layer/fused_transformer.py
    FusedMultiHeadAttention:121 — packed [3, H, D, E] qkv weight."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(
                f"num_heads ({num_heads}) must divide embed_dim "
                f"({embed_dim})")
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        H, D, E = num_heads, self.head_dim, embed_dim
        self.qkv_weight = self.create_parameter(
            [3, H, D, E], attr=qkv_weight_attr,
            default_initializer=XavierUniform())
        self.qkv_bias = self.create_parameter(
            [3 * E], attr=qkv_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.linear_weight = self.create_parameter(
            [E, E], attr=linear_weight_attr,
            default_initializer=XavierUniform())
        self.linear_bias = self.create_parameter(
            [E], attr=linear_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.pre_ln_scale = self.create_parameter(
            [E], attr=pre_ln_scale_attr, default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            [E], attr=pre_ln_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            [E], attr=ln_scale_attr, default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            [E], attr=ln_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        # like the reference FusedMultiHeadAttention: self-attention only
        # (raise rather than silently attending over query alone)
        if key is not None and key is not query:
            raise NotImplementedError(
                "FusedMultiHeadAttention supports self-attention only "
                "(reference contract); use nn.MultiHeadAttention for "
                "cross-attention")
        if cache is not None:
            raise NotImplementedError(
                "cache/generation: use the KV-cache decode path in "
                "models (KVCache)")
        return incubate_F.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self._epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, attn_mask=attn_mask,
            dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedFeedForward(Layer):
    """reference: fused_transformer.py FusedFeedForward:531."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._act = activation
        self._dropout = dropout_rate
        self._act_dropout = dropout_rate if act_dropout_rate is None else \
            act_dropout_rate
        self._normalize_before = normalize_before
        self._epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr,
            default_initializer=XavierUniform())
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr,
            default_initializer=XavierUniform())
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.ln1_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr, default_initializer=Constant(1.0))
        self.ln1_bias = self.create_parameter(
            [d_model], attr=ln1_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.ln2_scale = self.create_parameter(
            [d_model], attr=ln2_scale_attr, default_initializer=Constant(1.0))
        self.ln2_bias = self.create_parameter(
            [d_model], attr=ln2_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, src, cache=None):
        return incubate_F.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight, self.linear1_bias,
            self.linear2_bias, self.ln1_scale, self.ln1_bias, self.ln2_scale,
            self.ln2_bias, dropout1_rate=self._act_dropout,
            dropout2_rate=self._dropout, activation=self._act,
            ln1_epsilon=self._epsilon, ln2_epsilon=self._epsilon,
            pre_layer_norm=self._normalize_before, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """reference: fused_transformer.py FusedTransformerEncoderLayer:864 —
    fused attention block + fused FFN block."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False, name=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate if attn_dropout_rate is None
            else attn_dropout_rate, normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))
