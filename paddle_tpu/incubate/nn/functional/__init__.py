"""incubate.nn.functional fused ops (reference: python/paddle/incubate/nn/
functional — fused_multi_head_attention, fused_feedforward, fused_rms_norm,
fused_rotary_position_embedding, swiglu, fused_linear, fused_dropout_add).

TPU-native: 'fused' means ONE dispatched op whose body XLA/Pallas fuses —
attention rides the flash kernel; the rest are single apply_op bodies so the
whole epilogue chain compiles into one fusion instead of N kernel launches."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.dispatch import apply_op
from ....nn import functional as F
from ....nn.functional.activation import swiglu  # noqa: F401
from ....nn.functional.norm import rms_norm as fused_rms_norm  # noqa: F401
from ....nn.functional.rope import (  # noqa: F401
    fused_rotary_position_embedding)

__all__ = ["fused_multi_head_attention", "fused_feedforward",
           "fused_rms_norm", "fused_rotary_position_embedding", "swiglu",
           "fused_linear", "fused_dropout_add", "fused_bias_act"]


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """reference: incubate/nn/functional/fused_linear.py (matmul+bias in one
    op; the MXU epilogue applies the bias)."""
    def f(a, w, *b):
        w2 = w.T if transpose_weight else w
        y = a @ w2
        return y + b[0] if b else y
    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply_op("fused_linear", f, *args)


def fused_bias_act(x, bias=None, act_method="gelu", name=None):
    """reference: fused_bias_act_kernel — bias + activation one fusion."""
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "silu": jax.nn.silu, "swiglu": None}[act_method]

    def f(a, *b):
        h = a + b[0] if b else a
        if act_method == "swiglu":
            u, v = jnp.split(h, 2, axis=-1)
            return jax.nn.silu(u) * v
        return act(h)
    args = (x,) + ((bias,) if bias is not None else ())
    return apply_op("fused_bias_act", f, *args)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """reference: fused_dropout_add.py — dropout(x) + y in one op."""
    if not training or p == 0.0:
        return x + y
    from ....core.rng import next_key
    key = next_key()

    def f(a, b):
        keep = jax.random.bernoulli(key, 1.0 - p, jnp.shape(a))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0) + b
        return jnp.where(keep, a, 0.0) + b
    return apply_op("fused_dropout_add", f, x, y)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None,
                               ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               name=None):
    """reference: incubate/nn/functional/fused_transformer.py
    fused_multi_head_attention:345 — ln -> qkv -> attention -> proj ->
    dropout -> residual (+ln). Attention runs the flash path."""
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1:], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    B, S, E = x.shape
    # qkv_weight [3, H, D, E] (reference layout) or [E, 3E]
    qw = qkv_weight
    if qw.ndim == 4:
        H = qw.shape[1]
        D = qw.shape[2]

        def qkv_f(a, w, *b):
            y = jnp.einsum("bse,thde->bsthd", a, w)
            if b:
                y = y + b[0].reshape(3, H, D)[None, None]
            return y
        args = (x, qw) + ((qkv_bias,) if qkv_bias is not None else ())
        qkv = apply_op("fused_qkv", qkv_f, *args)
        q, k, v = (qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
    else:
        if num_heads is None:
            raise ValueError(
                "fused_multi_head_attention with a 2D qkv weight needs "
                "num_heads= (cannot be inferred from [E, 3E])")
        H = num_heads
        D = E // H
        y = fused_linear(x, qw, qkv_bias)
        q, k, v = [t.reshape([B, S, H, D]) for t in y.chunk(3, axis=-1)]
    out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                         dropout_p=attn_dropout_rate if
                                         training else 0.0, is_causal=False)
    out = out.reshape([B, S, H * D])
    out = fused_linear(out, linear_weight, linear_bias)
    if dropout_rate and training:
        out = F.dropout(out, p=dropout_rate, mode=mode)
    if add_residual:
        out = out + residual
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1:], ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode="upscale_in_train",
                      ring_id=-1, add_residual=True, name=None):
    """reference: fused_transformer.py fused_feedforward:121 —
    ln -> linear1 -> act -> dropout -> linear2 -> dropout -> residual."""
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1:], ln1_scale, ln1_bias, ln1_epsilon)
    h = fused_linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    if dropout1_rate and training:
        h = F.dropout(h, p=dropout1_rate, mode=mode)
    h = fused_linear(h, linear2_weight, linear2_bias)
    if dropout2_rate and training:
        h = F.dropout(h, p=dropout2_rate, mode=mode)
    if add_residual:
        h = h + residual
    if not pre_layer_norm:
        h = F.layer_norm(h, h.shape[-1:], ln2_scale, ln2_bias, ln2_epsilon)
    return h
