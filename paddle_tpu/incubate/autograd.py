"""incubate.autograd — functional jvp/vjp + Jacobian/Hessian aliases
(reference: python/paddle/incubate/autograd/__init__.py exporting jvp, vjp,
Jacobian, Hessian from functional.py).

TPU-native: vjp runs the eager tape backward with a supplied cotangent; jvp
lifts the user function into a jax.jvp over arrays — dispatch is
trace-transparent, so running `func` on tracer-backed Tensors records the same
ops it would eagerly, and forward-mode AD comes from XLA for free (the
reference implements jvp via double-vjp trickery instead).
"""
from __future__ import annotations

import jax

from ..core.tensor import Tensor
from ..core.dispatch import unwrap
from ..autograd.functional import jacobian as _jacobian, hessian as _hessian
from ..autograd.backward import grad as _grad

__all__ = ["jvp", "vjp", "Jacobian", "Hessian"]


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def vjp(func, xs, v=None):
    """(outputs, input-cotangents) of func at xs with output cotangent v
    (reference incubate/autograd/functional.py vjp).

    State-safe: computed via grad(only_inputs) — other leaves' .grad (e.g.
    model parameters mid-training) are untouched, and the inputs'
    stop_gradient/.grad are restored on exit."""
    xs_l = _as_list(xs)
    snap = [t.stop_gradient for t in xs_l]
    for t in xs_l:
        t.stop_gradient = False
    try:
        ys = func(*xs_l)
        ys_l = _as_list(ys)
        v_l = _as_list(v) if v is not None else None
        grads = _grad(ys_l, xs_l, grad_outputs=v_l, allow_unused=True)
    finally:
        for t, sg in zip(xs_l, snap):
            t.stop_gradient = sg
    single = not isinstance(xs, (list, tuple))
    return ys, grads[0] if single else grads


def Jacobian(func, xs, is_batched=False):
    """reference incubate/autograd/functional.py Jacobian: takes a CALLABLE
    and evaluation points; returns the full jacobian Tensor (sliceable, which
    covers the reference object's lazy-indexing surface)."""
    xs_l = _as_list(xs)
    snap = [t.stop_gradient for t in xs_l]
    for t in xs_l:
        t.stop_gradient = False
    try:
        ys = func(*xs_l)
        return _jacobian(ys, xs, batch_axis=0 if is_batched else None)
    finally:
        for t, sg in zip(xs_l, snap):
            t.stop_gradient = sg


def Hessian(func, xs, is_batched=False):
    """reference incubate/autograd/functional.py Hessian (callable-first)."""
    xs_l = _as_list(xs)
    snap = [t.stop_gradient for t in xs_l]
    for t in xs_l:
        t.stop_gradient = False
    try:
        ys = func(*xs_l)
        return _hessian(ys, xs, batch_axis=0 if is_batched else None)
    finally:
        for t, sg in zip(xs_l, snap):
            t.stop_gradient = sg


def jvp(func, xs, v=None):
    """(outputs, output-tangents) of func at xs with input tangent v —
    true forward-mode via jax.jvp over the lifted array function."""
    xs_l = _as_list(xs)
    primals = [unwrap(t) for t in xs_l]
    if v is None:
        import jax.numpy as jnp
        tangents = [jnp.ones_like(p) for p in primals]
    else:
        tangents = [unwrap(t) for t in _as_list(v)]

    def afn(*arrs):
        ts = [Tensor(a, stop_gradient=True) for a in arrs]
        out = func(*ts)
        out_l = _as_list(out)
        return tuple(unwrap(o) for o in out_l)

    out_arrs, tan_arrs = jax.jvp(afn, tuple(primals), tuple(tangents))
    outs = [Tensor(a, stop_gradient=True) for a in out_arrs]
    tans = [Tensor(a, stop_gradient=True) for a in tan_arrs]
    if len(outs) == 1:
        return outs[0], tans[0]
    return outs, tans
