"""paddle.incubate.distributed.models.moe (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py) — the in-core
TPU MoE (GShard dispatch over the ep mesh axis) IS this API."""
from .....parallel.moe import MoELayer, ExpertMLP, top2_gating  # noqa: F401

__all__ = ["MoELayer", "ExpertMLP", "top2_gating"]
