"""paddle.incubate.distributed.models (reference namespace)."""
from . import moe  # noqa: F401
