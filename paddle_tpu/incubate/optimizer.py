"""incubate.optimizer — LookAhead, ModelAverage (reference:
python/paddle/incubate/optimizer/{lookahead.py,modelaverage.py}).

Both are wrapper optimizers over an inner optimizer; slow weights / averages
live as jnp arrays keyed by parameter identity, so they shard exactly like the
parameters do under GSPMD (no host copies).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import unwrap

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k steps of the fast (inner) optimizer, then interpolate toward the
    slow weights: slow += alpha * (fast - slow); fast = slow
    (reference lookahead.py LookAhead.step)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be in [0, 1]")
        if k < 1:
            raise ValueError("k must be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_num = 0
        # slow weights seed at theta_0 (canonical Lookahead / reference
        # lookahead.py): the FIRST sync already pulls back toward init
        self._slow = {id(p): (p, unwrap(p))
                      for p in inner_optimizer._parameter_list}

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k:
            return
        for p in self.inner_optimizer._parameter_list:
            ent = self._slow.get(id(p))
            fast = unwrap(p)
            slow = ent[1] if ent is not None else fast   # late-added param
            slow = slow + self.alpha * (fast - slow)
            self._slow[id(p)] = (p, slow)
            p._data = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_num
        for i, p in enumerate(self.inner_optimizer._parameter_list):
            ent = self._slow.get(id(p))
            if ent is not None:
                sd[f"lookahead_slow_{i}"] = Tensor(ent[1], stop_gradient=True)
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)
        self._step_num = int(sd.pop("lookahead_step", 0))
        for i, p in enumerate(self.inner_optimizer._parameter_list):
            v = sd.pop(f"lookahead_slow_{i}", None)
            if v is not None:
                self._slow[id(p)] = (p, unwrap(v))
        self.inner_optimizer.set_state_dict(sd)

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)


class ModelAverage:
    """Maintains a running average of parameters; `apply()` swaps the
    averaged weights in (optionally restorable), for eval-time averaging
    (reference modelaverage.py — the EMA-style min/max_average_window
    windowing reduces to a plain running mean over the retained window)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters or [])
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        self._sum = {id(p): jnp.zeros_like(unwrap(p)) for p in self._params}
        self._cnt = 0
        self._total = 0
        self._backup = None

    def _window(self):
        """Effective window (reference modelaverage.py): grows as
        rate * total_updates, clamped to [min_average_window,
        max_average_window]."""
        grown = int(self._rate * max(self._total, 1))
        return max(self._min_w, min(self._max_w, max(grown, 1)))

    def step(self):
        """Accumulate the current weights (call after optimizer.step)."""
        self._total += 1
        if self._cnt >= self._window():
            # restart the window, keeping the current average as the seed
            for p in self._params:
                self._sum[id(p)] = self._sum[id(p)] / max(self._cnt, 1)
            self._cnt = 1
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + unwrap(p)
        self._cnt += 1

    def apply(self, executor=None, need_restore=True):
        """Swap averaged weights into the model (context-manager friendly)."""
        if self._cnt == 0:
            return self
        self._backup = {id(p): unwrap(p) for p in self._params}
        for p in self._params:
            p._data = (self._sum[id(p)] / self._cnt).astype(unwrap(p).dtype)
        if not need_restore:
            self._backup = None
        return self

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[id(p)]
        self._backup = None

    def __enter__(self):
        return self.apply()

    def __exit__(self, *exc):
        self.restore()
