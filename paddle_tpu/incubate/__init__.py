"""paddle.incubate (reference: python/paddle/incubate) — fused layers + MoE."""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401

# reference incubate/__init__.py __all__ surface
from ..geometric import (segment_sum, segment_mean, segment_max,  # noqa: F401
                         segment_min)
from ..geometric import (send_u_recv as graph_send_recv,  # noqa: F401
                         reindex_graph as graph_reindex,
                         sample_neighbors as graph_sample_neighbors)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """reference incubate/operators/graph_khop_sampler.py -> (edge_src,
    edge_dst, sample_index): multi-hop neighbor sampling, edges reindexed
    into the unique-node numbering (input nodes first, then first-seen)."""
    if return_eids:
        raise NotImplementedError("graph_khop_sampler return_eids")
    from ..geometric import sample_neighbors
    from ..geometric import _first_seen_remap
    import numpy as _np
    from ..core.tensor import Tensor as _T
    from ..core.dispatch import unwrap as _u
    import jax.numpy as _jnp
    sizes = list(sample_sizes)
    frontier = _np.asarray(_u(input_nodes)).reshape(-1)
    src_parts, dst_parts = [], []
    for k in sizes:
        n, c = sample_neighbors(row, colptr, _T(_jnp.asarray(frontier)),
                                sample_size=k)
        nv = _np.asarray(_u(n)).reshape(-1)
        cv = _np.asarray(_u(c)).reshape(-1)
        src_parts.append(nv)
        dst_parts.append(_np.repeat(frontier, cv))
        frontier = _np.unique(nv) if nv.size else frontier
    src = _np.concatenate(src_parts) if src_parts else _np.zeros(0, _np.int64)
    dst = _np.concatenate(dst_parts) if dst_parts else _np.zeros(0, _np.int64)
    start = _np.asarray(_u(input_nodes)).reshape(-1)
    remap, nodes = _first_seen_remap([start, src, dst])
    return (_T(_jnp.asarray(remap(src))), _T(_jnp.asarray(remap(dst))),
            _T(_jnp.asarray(nodes)))


def softmax_mask_fuse(x, mask, name=None):
    """reference incubate softmax_mask_fuse: softmax(x + mask) fused (XLA
    fuses the add into the softmax; the CUDA op exists for the same reason)."""
    from ..core.dispatch import apply_op
    import jax
    import jax.numpy as jnp

    def f(a, m):
        return jax.nn.softmax(a.astype(jnp.float32) + m.astype(jnp.float32),
                              axis=-1).astype(a.dtype)
    return apply_op("softmax_mask_fuse", f, x, mask)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """reference softmax_mask_fuse_upper_triangle: causal-masked softmax."""
    from ..core.dispatch import apply_op
    import jax
    import jax.numpy as jnp

    def f(a):
        S = a.shape[-1]
        mask = jnp.tril(jnp.ones((a.shape[-2], S), bool), k=S - a.shape[-2])
        z = jnp.where(mask, a.astype(jnp.float32), -1e30)
        return jax.nn.softmax(z, axis=-1).astype(a.dtype)
    return apply_op("softmax_mask_fuse_upper_triangle", f, x)


def identity_loss(x, reduction="none"):
    """reference incubate identity_loss (IPU-era): pass-through loss marker."""
    if reduction in (0, "sum"):
        return x.sum()
    if reduction in (1, "mean"):
        return x.mean()
    return x


from . import inference  # noqa: F401,E402
