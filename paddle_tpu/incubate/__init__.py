"""paddle.incubate (reference: python/paddle/incubate) — fused layers + MoE."""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401

# reference incubate/__init__.py __all__ surface
from ..geometric import (segment_sum, segment_mean, segment_max,  # noqa: F401
                         segment_min)
from ..geometric import (send_u_recv as graph_send_recv,  # noqa: F401
                         reindex_graph as graph_reindex,
                         sample_neighbors as graph_sample_neighbors)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """reference incubate/operators/graph_khop_sampler.py: multi-hop neighbor
    sampling — one sample_neighbors pass per hop, frontier = prior outputs."""
    from ..geometric import sample_neighbors
    import numpy as _np
    from ..core.tensor import Tensor as _T
    from ..core.dispatch import unwrap as _u
    import jax.numpy as _jnp
    frontier = input_nodes
    rows_out, counts_out = [], []
    if not list(sample_sizes):
        z = _T(_jnp.zeros(0, _jnp.int32))
        return z, _T(_jnp.zeros(0, _jnp.int32))
    for k in sample_sizes:
        n, c = sample_neighbors(row, colptr, frontier, sample_size=k)
        rows_out.append(_np.asarray(_u(n)))
        counts_out.append(_np.asarray(_u(c)))
        frontier = _T(_jnp.asarray(_np.unique(_np.asarray(_u(n)))))
    edges = _np.concatenate(rows_out) if rows_out else _np.zeros(0, _np.int64)
    return (_T(_jnp.asarray(edges)),
            _T(_jnp.asarray(_np.concatenate(counts_out).astype(_np.int32))))


def softmax_mask_fuse(x, mask, name=None):
    """reference incubate softmax_mask_fuse: softmax(x + mask) fused (XLA
    fuses the add into the softmax; the CUDA op exists for the same reason)."""
    from ..core.dispatch import apply_op
    import jax
    import jax.numpy as jnp

    def f(a, m):
        return jax.nn.softmax(a.astype(jnp.float32) + m.astype(jnp.float32),
                              axis=-1).astype(a.dtype)
    return apply_op("softmax_mask_fuse", f, x, mask)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """reference softmax_mask_fuse_upper_triangle: causal-masked softmax."""
    from ..core.dispatch import apply_op
    import jax
    import jax.numpy as jnp

    def f(a):
        S = a.shape[-1]
        mask = jnp.tril(jnp.ones((a.shape[-2], S), bool), k=S - a.shape[-2])
        z = jnp.where(mask, a.astype(jnp.float32), -1e30)
        return jax.nn.softmax(z, axis=-1).astype(a.dtype)
    return apply_op("softmax_mask_fuse_upper_triangle", f, x)


def identity_loss(x, reduction="none"):
    """reference incubate identity_loss (IPU-era): pass-through loss marker."""
    from .. import ops
    if reduction in (0, "sum"):
        return x.sum()
    if reduction in (1, "mean"):
        return x.mean()
    return x


from . import inference  # noqa: F401,E402
