"""paddle.incubate (reference: python/paddle/incubate) — fused layers + MoE.
Fused transformer/MoE surfaces land with the parallel layer library."""
from . import nn  # noqa: F401
