"""incubate.inference (reference: python/paddle/incubate/inference/__init__.py
exporting the `predictor` conversion decorator for LLM serving).

TPU realization: the decorator jit-compiles the wrapped callable's forward
via paddle.jit.to_static — the serving predictor path proper lives in
paddle_tpu.inference (Config/Predictor over jit.save artifacts).
"""
from __future__ import annotations

__all__ = ["predictor"]


def predictor(function=None, *, cache_static_model=False, **kwargs):
    """Decorator: compile a callable (or a Layer's forward) for serving."""
    from ..jit import to_static

    def deco(fn):
        return to_static(fn)
    if function is not None:
        return deco(function)
    return deco
