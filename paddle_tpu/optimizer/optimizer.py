"""Optimizer base (reference: python/paddle/optimizer/optimizer.py:127).

State accumulators live in Tensors (persistable), so a jitted train step captures
them as donated inputs/outputs automatically. Updates compute in float32 master
precision when parameters are bf16/f16 and multi_precision is set.
"""
from __future__ import annotations

from collections import defaultdict

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core.dispatch import unwrap
from ..nn.clip import ClipGradBase


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        from .lr import LRScheduler
        self._lr = learning_rate
        self._lr_scheduler = learning_rate if isinstance(learning_rate, LRScheduler) else None
        # LR lives in a persistable Tensor so a captured train step reads it as a
        # program input (scheduler.step() outside the capture updates it) instead
        # of baking the first step's float as a constant.
        lr0 = float(self._lr_scheduler()) if self._lr_scheduler is not None else float(learning_rate)
        self._lr_t = Tensor(jnp.asarray(lr0, jnp.float32), persistable=True)
        self._lr_t.name = "learning_rate"
        if self._lr_scheduler is not None:
            if not hasattr(self._lr_scheduler, "_bound_opts"):
                self._lr_scheduler._bound_opts = []
            self._lr_scheduler._bound_opts.append(self)
        if parameters is None:
            raise ValueError("parameters must be provided (dygraph-style optimizer)")
        self._param_groups = self._build_groups(parameters)
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._accumulators: dict[str, dict[int, Tensor]] = defaultdict(dict)
        self._global_step = Tensor(jnp.zeros((), jnp.int32), persistable=True)
        self._multi_precision = False

    def _build_groups(self, parameters):
        params = list(parameters)
        if params and isinstance(params[0], dict):
            groups = []
            for g in params:
                g = dict(g)
                g["params"] = list(g["params"])
                groups.append(g)
            return groups
        return [{"params": params}]

    @property
    def _parameter_list(self):
        return [p for g in self._param_groups for p in g["params"]]

    # ---- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler())
        return float(self._lr)

    def set_lr(self, value):
        if self._lr_scheduler is not None:
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = value
        self._lr_t._data = jnp.asarray(float(value), jnp.float32)

    def set_lr_scheduler(self, scheduler):
        self._lr_scheduler = scheduler

    # ---- accumulators --------------------------------------------------------
    def _acc(self, name, p, init=None, dtype=None):
        store = self._accumulators[name]
        key = id(p)
        if key not in store:
            dt = dtype or (jnp.float32 if self._multi_precision else p._data.dtype)
            arr = jnp.zeros(p._data.shape, dt) if init is None else init
            t = Tensor(arr, persistable=True)
            t.name = f"{name}_{p.name or key}"
            store[key] = t
        return store[key]

    # ---- step ----------------------------------------------------------------
    def step(self):
        lr = unwrap(self._lr_t)  # 0-d array (tracer under capture)
        # clip over ALL groups at once so ClipGradByGlobalNorm sees the true
        # global norm (reference: Optimizer._create_optimization_pass clips the
        # concatenated params_grads)
        from ..core.selected_rows import SelectedRows
        all_pg = [(p, p.grad) for g in self._param_groups for p in g["params"]
                  if not p.stop_gradient and p.grad is not None]
        sparse_pg = [(p, g) for p, g in all_pg
                     if isinstance(g, SelectedRows)]
        all_pg = [(p, g) for p, g in all_pg
                  if not isinstance(g, SelectedRows)]
        if self._grad_clip is not None:
            # global-norm clip skips row-sparse grads (reference restricts
            # sparse grads the same way)
            all_pg = self._grad_clip(all_pg)
        clipped = {id(p): g for p, g in all_pg}
        clipped.update({id(p): g for p, g in sparse_pg})
        for group in self._param_groups:
            glr = lr * group.get("learning_rate", 1.0)
            wd = group.get("weight_decay", self._weight_decay)
            for p in group["params"]:
                g = clipped.get(id(p))
                if g is None:
                    continue
                plr = glr * p.optimize_attr.get("learning_rate", 1.0) \
                    if isinstance(p, Parameter) else glr
                if isinstance(g, SelectedRows):
                    self._update_param_sparse(p, g, plr, wd)
                else:
                    self._update_param(p, unwrap(g), plr, wd)
        self._global_step._data = unwrap(self._global_step) + 1

    def _update_param(self, p, g, lr, weight_decay):
        raise NotImplementedError

    def _update_param_sparse(self, p, g, lr, weight_decay):
        """Row-sparse (SelectedRows) update. Optimizers with a true sparse
        rule override this (SGD scatters row deltas); the default densifies
        — correct for any optimizer, without the bandwidth win."""
        self._update_param(p, g.to_dense(), lr, weight_decay)

    # ---- master weights ------------------------------------------------------
    def _master(self, p):
        """(master_tensor_or_None, f32 working value).

        With multi_precision set and a low-precision parameter, keep a
        persistent f32 master copy as the update's source of truth — otherwise
        updates smaller than one bf16 ulp are permanently lost (reference:
        adamw multi_precision master-weight path,
        python/paddle/optimizer/adamw.py)."""
        if self._multi_precision and p._data.dtype in (jnp.bfloat16, jnp.float16):
            mw = self._acc("master_weight", p, dtype=jnp.float32,
                           init=unwrap(p).astype(jnp.float32))
            return mw, unwrap(mw)
        return None, unwrap(p).astype(jnp.float32)

    def _commit(self, p, mw, pw):
        """Store the updated f32 value: master keeps full precision, the model
        copy is a cast-down view."""
        if mw is not None:
            mw._data = pw
        p._data = pw.astype(p._data.dtype)

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    # ---- state dict ----------------------------------------------------------
    def state_dict(self):
        sd = {}
        plist = self._parameter_list
        for name, store in self._accumulators.items():
            for i, p in enumerate(plist):
                if id(p) in store:
                    sd[f"{name}_{i}"] = store[id(p)]
        sd["global_step"] = self._global_step
        if self._lr_scheduler is not None:
            sd["LR_Scheduler"] = self._lr_scheduler.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        plist = self._parameter_list
        for key, value in state_dict.items():
            if key == "LR_Scheduler":
                if self._lr_scheduler is not None:
                    self._lr_scheduler.set_state_dict(value)
                continue
            if key == "global_step":
                self._global_step._data = unwrap(value) if isinstance(value, Tensor) \
                    else jnp.asarray(value)
                continue
            name, _, idx = key.rpartition("_")
            p = plist[int(idx)]
            v = value._data if isinstance(value, Tensor) else jnp.asarray(np.asarray(value))
            # create with the SAVED dtype: creating with the parameter dtype
            # would silently downcast checkpointed f32 Adam moments to bf16 on
            # resume, degrading training after restart
            t = self._acc(name, p, dtype=v.dtype)
            t._data = v.astype(t._data.dtype)

    def _apply_weight_decay_l2(self, pw, g, wd):
        """Fold regularizer into grad (SGD/Momentum/Adam style): L2 adds coeff*p,
        L1 adds coeff*sign(p) (reference: python/paddle/regularizer.py).
        `pw` is the f32 working value of the parameter (master weight if set)."""
        if wd is None:
            return g
        coeff = wd.coeff if hasattr(wd, "coeff") else float(wd)
        if isinstance(wd, L1Decay):
            return g + coeff * jnp.sign(pw)
        return g + coeff * pw


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = coeff


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = coeff
