"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,adam,adamw,...}.py).

All moment math in float32 (bf16-first training contract); parameter updates cast
back to the parameter dtype at the end (master-weights behavior when
multi_precision=True keeps an f32 copy as the source of truth).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import unwrap
from .optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._multi_precision = multi_precision

    def _update_param(self, p, g, lr, wd):
        mw, pw = self._master(p)
        g = self._apply_weight_decay_l2(pw, g.astype(jnp.float32), wd)
        self._commit(p, mw, pw - lr * g)

    def _update_param_sparse(self, p, g, lr, wd):
        """True sparse SGD: only the touched rows move (reference
        sgd sparse kernel over SelectedRows)."""
        sr = g.merge()
        mw, pw = self._master(p)
        rows = sr.rows
        delta = lr * sr.values.astype(jnp.float32)
        if wd:
            delta = delta + lr * wd * pw[rows].astype(jnp.float32)
        self._commit(p, mw, pw.at[rows].add(-delta.astype(pw.dtype)))


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov
        self._multi_precision = multi_precision

    def _update_param(self, p, g, lr, wd):
        mw, pw = self._master(p)
        g = self._apply_weight_decay_l2(pw, g.astype(jnp.float32), wd)
        vel = self._acc("velocity", p, dtype=jnp.float32)
        v = self._momentum * unwrap(vel) + g
        vel._data = v
        if self._nesterov:
            update = g + self._momentum * v
        else:
            update = v
        self._commit(p, mw, pw - lr * update)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._multi_precision = multi_precision
        self._amsgrad = amsgrad

    def _decay_is_decoupled(self):
        return False

    def _update_param(self, p, g, lr, wd):
        mw, pw = self._master(p)
        gf = g.astype(jnp.float32)
        if not self._decay_is_decoupled():
            gf = self._apply_weight_decay_l2(pw, gf, wd)
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=jnp.ones((), jnp.float32))
        b2p = self._acc("beta2_pow", p, init=jnp.ones((), jnp.float32))
        b1t = unwrap(b1p) * self._beta1
        b2t = unwrap(b2p) * self._beta2
        b1p._data, b2p._data = b1t, b2t
        mv = self._beta1 * unwrap(m) + (1 - self._beta1) * gf
        vv = self._beta2 * unwrap(v) + (1 - self._beta2) * jnp.square(gf)
        m._data, v._data = mv, vv
        if self._amsgrad:
            vmax = self._acc("moment2_max", p, dtype=jnp.float32)
            vv = jnp.maximum(unwrap(vmax), vv)
            vmax._data = vv
        mhat = mv / (1 - b1t)
        vhat = vv / (1 - b2t)
        if self._decay_is_decoupled() and wd is not None:
            coeff = wd if isinstance(wd, float) else getattr(wd, "coeff", 0.0)
            if self._should_decay(p):
                pw = pw * (1.0 - lr * coeff)
        self._commit(p, mw, pw - lr * mhat / (jnp.sqrt(vhat) + self._eps))

    def _should_decay(self, p):
        return True


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, amsgrad=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision, amsgrad)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decay_is_decoupled(self):
        return True

    def _should_decay(self, p):
        if self._apply_decay_param_fun is not None:
            return self._apply_decay_param_fun(p.name)
        return True


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _update_param(self, p, g, lr, wd):
        mw, pw = self._master(p)
        gf = self._apply_weight_decay_l2(pw, g.astype(jnp.float32), wd)
        m = self._acc("moment", p, dtype=jnp.float32)
        u = self._acc("inf_norm", p, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=jnp.ones((), jnp.float32))
        b1t = unwrap(b1p) * self._beta1
        b1p._data = b1t
        mv = self._beta1 * unwrap(m) + (1 - self._beta1) * gf
        uv = jnp.maximum(self._beta2 * unwrap(u), jnp.abs(gf))
        m._data, u._data = mv, uv
        self._commit(p, mw, pw - lr / (1 - b1t) * mv / (uv + self._eps))


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, g, lr, wd):
        mw, pw = self._master(p)
        gf = self._apply_weight_decay_l2(pw, g.astype(jnp.float32), wd)
        acc = self._acc("moment", p,
                        init=jnp.full(p._data.shape, self._init_acc, jnp.float32))
        av = unwrap(acc) + jnp.square(gf)
        acc._data = av
        self._commit(p, mw, pw - lr * gf / (jnp.sqrt(av) + self._eps))


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _update_param(self, p, g, lr, wd):
        mw, pw = self._master(p)
        gf = self._apply_weight_decay_l2(pw, g.astype(jnp.float32), wd)
        ms = self._acc("mean_square", p, dtype=jnp.float32)
        mom = self._acc("momentum", p, dtype=jnp.float32)
        msv = self._rho * unwrap(ms) + (1 - self._rho) * jnp.square(gf)
        ms._data = msv
        if self._centered:
            mg = self._acc("mean_grad", p, dtype=jnp.float32)
            mgv = self._rho * unwrap(mg) + (1 - self._rho) * gf
            mg._data = mgv
            denom = jnp.sqrt(msv - jnp.square(mgv) + self._eps)
        else:
            denom = jnp.sqrt(msv + self._eps)
        mv = self._momentum * unwrap(mom) + lr * gf / denom
        mom._data = mv
        self._commit(p, mw, pw - mv)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps, self._rho = epsilon, rho

    def _update_param(self, p, g, lr, wd):
        mw, pw = self._master(p)
        gf = self._apply_weight_decay_l2(pw, g.astype(jnp.float32), wd)
        avg_sq = self._acc("avg_squared_grad", p, dtype=jnp.float32)
        avg_up = self._acc("avg_squared_update", p, dtype=jnp.float32)
        asv = self._rho * unwrap(avg_sq) + (1 - self._rho) * jnp.square(gf)
        update = jnp.sqrt(unwrap(avg_up) + self._eps) / jnp.sqrt(asv + self._eps) * gf
        auv = self._rho * unwrap(avg_up) + (1 - self._rho) * jnp.square(update)
        avg_sq._data, avg_up._data = asv, auv
        self._commit(p, mw, pw - lr * update)


class Lamb(Optimizer):
    """Layer-wise adaptive moments (reference: python/paddle/optimizer/lamb.py)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn
        self._multi_precision = multi_precision

    def _update_param(self, p, g, lr, wd):
        gf = g.astype(jnp.float32)
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=jnp.ones((), jnp.float32))
        b2p = self._acc("beta2_pow", p, init=jnp.ones((), jnp.float32))
        b1t, b2t = unwrap(b1p) * self._beta1, unwrap(b2p) * self._beta2
        b1p._data, b2p._data = b1t, b2t
        mv = self._beta1 * unwrap(m) + (1 - self._beta1) * gf
        vv = self._beta2 * unwrap(v) + (1 - self._beta2) * jnp.square(gf)
        m._data, v._data = mv, vv
        mhat = mv / (1 - b1t)
        vhat = vv / (1 - b2t)
        mw, pw = self._master(p)
        r = mhat / (jnp.sqrt(vhat) + self._eps)
        if self._exclude_fn is None or not self._exclude_fn(p):
            r = r + self._wd * pw
        w_norm = jnp.linalg.norm(pw)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        self._commit(p, mw, pw - lr * trust * r)


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._max_iter = max_iter
        self._history_size = history_size
        self._old_dirs: list = []
        self._old_stps: list = []
        self._prev_flat_grad = None

    def step(self, closure=None):
        """Simplified two-loop-recursion L-BFGS (eager-only; host control flow)."""
        if closure is not None:
            loss = closure()
        params = [p for p in self._parameter_list if p.grad is not None]
        if not params:
            return
        flat_g = jnp.concatenate([unwrap(p.grad).astype(jnp.float32).reshape(-1)
                                  for p in params])
        if self._prev_flat_grad is not None:
            y = flat_g - self._prev_flat_grad
            s = self._last_step
            ys = jnp.dot(y, s)
            if float(ys) > 1e-10:
                self._old_dirs.append(y)
                self._old_stps.append(s)
                if len(self._old_dirs) > self._history_size:
                    self._old_dirs.pop(0)
                    self._old_stps.pop(0)
        q = flat_g
        alphas = []
        for y, s in zip(reversed(self._old_dirs), reversed(self._old_stps)):
            rho = 1.0 / jnp.dot(y, s)
            alpha = rho * jnp.dot(s, q)
            q = q - alpha * y
            alphas.append((alpha, rho))
        if self._old_dirs:
            y, s = self._old_dirs[-1], self._old_stps[-1]
            q = q * (jnp.dot(y, s) / jnp.dot(y, y))
        for (alpha, rho), (y, s) in zip(reversed(alphas),
                                        zip(self._old_dirs, self._old_stps)):
            beta = rho * jnp.dot(y, q)
            q = q + (alpha - beta) * s
        direction = -q
        lr = self.get_lr()
        self._last_step = lr * direction
        self._prev_flat_grad = flat_g
        offset = 0
        for p in params:
            n = p.size
            upd = self._last_step[offset:offset + n].reshape(p._data.shape)
            p._data = (unwrap(p).astype(jnp.float32) + upd).astype(p._data.dtype)
            offset += n


class NAdam(Optimizer):
    """reference: optimizer/nadam.py — Adam with Nesterov momentum
    (Dozat 2016; mu-product schedule)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _update_param(self, p, g, lr, wd):
        mw, pw = self._master(p)
        gf = self._apply_weight_decay_l2(pw, g.astype(jnp.float32), wd)
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        tacc = self._acc("step", p, init=jnp.zeros((), jnp.float32))
        mu_prod = self._acc("mu_product", p, init=jnp.ones((), jnp.float32))
        t = unwrap(tacc) + 1.0
        tacc._data = t
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mp = unwrap(mu_prod) * mu_t
        mu_prod._data = mp
        mv = self._beta1 * unwrap(m) + (1 - self._beta1) * gf
        vv = self._beta2 * unwrap(v) + (1 - self._beta2) * gf * gf
        m._data, v._data = mv, vv
        m_hat = mu_t1 * mv / (1 - mp * mu_t1) + (1 - mu_t) * gf / (1 - mp)
        v_hat = vv / (1 - self._beta2 ** t)
        self._commit(p, mw, pw - lr * m_hat / (jnp.sqrt(v_hat) + self._eps))


class RAdam(Optimizer):
    """reference: optimizer/radam.py — rectified Adam (Liu et al. 2020)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _update_param(self, p, g, lr, wd):
        mw, pw = self._master(p)
        gf = self._apply_weight_decay_l2(pw, g.astype(jnp.float32), wd)
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        tacc = self._acc("step", p, init=jnp.zeros((), jnp.float32))
        t = unwrap(tacc) + 1.0
        tacc._data = t
        mv = self._beta1 * unwrap(m) + (1 - self._beta1) * gf
        vv = self._beta2 * unwrap(v) + (1 - self._beta2) * gf * gf
        m._data, v._data = mv, vv
        m_hat = mv / (1 - self._beta1 ** t)
        rho_inf = 2.0 / (1 - self._beta2) - 1.0
        b2t = self._beta2 ** t
        rho_t = rho_inf - 2.0 * t * b2t / (1 - b2t)
        # variance rectification: plain momentum until rho_t > 5
        # (reference radam.py:66 and torch both gate at 5)
        def rect():
            # clamp keeps the unselected branch NaN-free for rho_t in (2, 4)
            # (jnp.where evaluates both sides; jax_debug_nans would trip)
            num = jnp.maximum((rho_t - 4) * (rho_t - 2) * rho_inf, 0.0)
            r = jnp.sqrt(num / ((rho_inf - 4) * (rho_inf - 2) * rho_t))
            v_hat = jnp.sqrt(vv / (1 - b2t))
            return r * m_hat / (v_hat + self._eps)
        upd = jnp.where(rho_t > 5.0, rect(), m_hat)
        self._commit(p, mw, pw - lr * upd)


class Rprop(Optimizer):
    """reference: optimizer/rprop.py — resilient backprop (sign-based
    per-weight step sizes; full-batch regime)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 name=None, weight_decay=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_minus, self._eta_plus = etas

    def _update_param(self, p, g, lr, wd):
        mw, pw = self._master(p)
        gf = self._apply_weight_decay_l2(pw, g.astype(jnp.float32), wd)
        prev = self._acc("prev_grad", p, dtype=jnp.float32)
        step = self._acc("step_size", p, dtype=jnp.float32,
                         init=jnp.full(p.shape, float(lr), jnp.float32))
        sgn = jnp.sign(unwrap(prev) * gf)
        factor = jnp.where(sgn > 0, self._eta_plus,
                           jnp.where(sgn < 0, self._eta_minus, 1.0))
        ns = jnp.clip(unwrap(step) * factor, self._lr_min, self._lr_max)
        g_eff = jnp.where(sgn < 0, 0.0, gf)   # backtrack: skip update
        step._data = ns
        prev._data = g_eff
        self._commit(p, mw, pw - ns * jnp.sign(g_eff))


class ASGD(Optimizer):
    """reference: optimizer/asgd.py — Stochastic Average Gradient (SAG):
    keep the last-seen gradient y_i per batch slot, maintain their running
    sum d, step with the averaged gradient d / min(m+1, n)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._n = int(max(batch_num, 1))

    def _update_param(self, p, g, lr, wd):
        mw, pw = self._master(p)
        gf = self._apply_weight_decay_l2(pw, g.astype(jnp.float32), wd)
        n = self._n
        d = self._acc("d", p, dtype=jnp.float32)
        ys = self._acc("ys", p, init=jnp.zeros((n,) + tuple(p.shape),
                                               jnp.float32))
        macc = self._acc("m", p, init=jnp.zeros((), jnp.int32))
        m = unwrap(macc)
        i = m % n
        yi = jax.lax.dynamic_index_in_dim(unwrap(ys), i, keepdims=False)
        dv = unwrap(d) - yi + gf
        d._data = dv
        ys._data = jax.lax.dynamic_update_index_in_dim(
            unwrap(ys), gf, i, axis=0)
        macc._data = m + 1
        denom = jnp.minimum(m + 1, n).astype(jnp.float32)
        self._commit(p, mw, pw - lr * dv / denom)
