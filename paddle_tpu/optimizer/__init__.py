"""paddle.optimizer surface (reference: python/paddle/optimizer/__init__.py)."""
from .optimizer import Optimizer, L1Decay, L2Decay  # noqa: F401
from .optimizers import (SGD, Momentum, Adam, AdamW, Adamax, Adagrad, RMSProp,  # noqa: F401
                         Adadelta, Lamb, LBFGS, NAdam,
                         RAdam, Rprop, ASGD)
from . import lr  # noqa: F401
