"""Host scheduling: admission, slot tables, deadlines, preemption, and the
request lifecycle — everything between ``add_request`` and a terminal status
that does not touch a device buffer.

:class:`Scheduler` owns the waiting queue, the per-slot page tables /
lengths, and the finished map; it allocates through a
:class:`~.pages.PagePool` and the only device operation it can trigger is
the injected ``copy_page`` callable (the copy half of copy-on-write, bound
to :meth:`~.runner.ModelRunner.copy_page` by the engine).  The
:class:`~.core.LLMEngine` facade drives it: ``admit()`` at step entry,
``emit()`` per generated token, ``release()/preempt_youngest()`` on the
failure and pool-pressure paths.

``detach()`` / ``admit_prefilled()`` are the disaggregation seam: detach
lifts a freshly-prefilled request out of its slot WITHOUT dropping its page
references (ownership moves to the caller — the KV handoff queue), and
admit_prefilled seats a request whose pages were written elsewhere, skipping
prefill entirely.
"""
from __future__ import annotations

import math
import time
from collections import deque

import numpy as np

from ...observability import flight as _flight
from .request import RequestStatus, prefix_page_keys

__all__ = ["Scheduler"]


class Scheduler:
    """Continuous-batching scheduler over one PagePool."""

    def __init__(self, pool, max_batch, max_len, page_size, pages_per_slot,
                 prefix_cache=False, copy_page=None, metrics=None,
                 max_waiting=None, shed_min_free_ratio=0.0,
                 restore_chain=None):
        self.pool = pool
        self.max_batch = int(max_batch)
        self.max_len = int(max_len)
        self.page = int(page_size)
        self.pages_per_slot = int(pages_per_slot)
        self.prefix_cache = bool(prefix_cache)
        self._copy_page = copy_page          # device page copy (CoW)
        # host-tier restore: restore_chain([keys]) -> physical pages it
        # managed to bring back on-device, in order (engine-injected, same
        # contract as copy_page — may be shorter than asked on failure)
        self._restore_chain = restore_chain
        self._m = metrics
        self.max_waiting = None if max_waiting is None else int(max_waiting)
        self.shed_min_free_ratio = float(shed_min_free_ratio)
        self.slots: list = [None] * self.max_batch
        self.slot_tables = np.zeros((self.max_batch, self.pages_per_slot),
                                    np.int32)
        self.lens = np.zeros((self.max_batch,), np.int32)
        self.n_alloc = np.zeros((self.max_batch,), np.int32)
        self.waiting: deque = deque()
        self.finished: dict = {}
        self._admit_seq = 0
        self.preemptions = 0
        self.shed_requests = 0          # refused by admission control
        self.timeouts = 0               # deadline expiries (waiting + active)
        self.cancels = 0                # cancel(rid) that found the request
        self.quarantined = 0            # requests isolated as FAILED

    # ----------------------------------------------------- request lifecycle
    def should_shed(self):
        """Watermark admission control over the same gauges metrics()
        exports: a bounded waiting queue, plus a page-pressure floor that
        sheds while a backlog already exists (an idle engine always admits —
        a single fresh request can still run via preemption)."""
        if self.max_waiting is not None \
                and len(self.waiting) >= self.max_waiting:
            return True
        if self.shed_min_free_ratio > 0.0 and self.waiting:
            # LRU pages the host tier could absorb are reclaimable WITHOUT
            # recompute loss, so with a spill tier attached the same
            # watermark sheds later
            avail = self.pool.n_available(host_headroom=True)
            if avail < self.shed_min_free_ratio * self.pool.n_usable:
                return True
        return False

    def finalize(self, r, status, error=None):
        """Move ``r`` to its typed terminal status (the ONLY path into
        ``finished``), mirroring the terminal counters."""
        r.status = status
        r.done = True
        r.slot = None
        if error is not None:
            r.error = f"{type(error).__name__}: {error}"
        r.t_finish = time.perf_counter()
        self.finished[r.rid] = r
        if r.trace_id is not None:
            _flight.record("terminal", rid=r.rid, trace_id=r.trace_id,
                           status=status.value, error=r.error,
                           tokens=len(r.out))
        if status is RequestStatus.SHED:
            self.shed_requests += 1
        elif status is RequestStatus.TIMEOUT:
            self.timeouts += 1
        elif status is RequestStatus.CANCELLED:
            self.cancels += 1
        elif status is RequestStatus.FAILED:
            self.quarantined += 1
        if self._m is not None:
            self._m.terminal[status].inc()

    def cancel(self, rid):
        """Cancel a request wherever it is: waiting (dequeued) or mid-serve
        (slot released — pages return through the refcount machinery, so
        prefix-cache pages other slots share stay live).  Returns True if
        the request was found live; False if unknown or already terminal."""
        for i, r in enumerate(self.waiting):
            if r.rid == rid:
                del self.waiting[i]
                self.finalize(r, RequestStatus.CANCELLED)
                return True
        for slot, r in enumerate(self.slots):
            if r is not None and r.rid == rid:
                self.release(slot, RequestStatus.CANCELLED)
                return True
        return False

    def expire_deadlines(self):
        """Deadline sweep at step entry: expired waiting requests are shed
        unserved; an expired in-flight request finalizes cleanly (partial
        output kept, pages released).  Both end TIMEOUT."""
        now = time.perf_counter()
        if self.waiting:
            expired = [r for r in self.waiting
                       if r.deadline is not None and now > r.deadline]
            if expired:
                keep = deque(r for r in self.waiting
                             if not (r.deadline is not None
                                     and now > r.deadline))
                self.waiting.clear()
                self.waiting.extend(keep)
                for r in expired:
                    self.finalize(r, RequestStatus.TIMEOUT)
        for slot, r in enumerate(self.slots):
            if r is not None and r.deadline is not None and now > r.deadline:
                self.release(slot, RequestStatus.TIMEOUT)

    # ------------------------------------------------------ page accounting
    def page_keys(self, tokens):
        """Chain keys of ``tokens``' full pages (see
        :func:`~.request.prefix_page_keys` — shared with the frontend
        router)."""
        return prefix_page_keys(tokens, self.page)

    def cow_unshare(self, slot, start, n):
        """Copy-on-write before a prefill write into [start, start+n): any
        touched page another slot still maps (refcount > 1) gets a private
        copy so the write can't clobber the shared prefix. Hit on exactly
        one path: a fully-cached prompt re-prefills its final token into the
        last shared page."""
        pool = self.pool
        for j in range(start // self.page, (start + n - 1) // self.page + 1):
            p = int(self.slot_tables[slot, j])
            while int(pool.page_ref[p]) > 1:
                # RL102 sees preempt_youngest between alloc and rollback,
                # but it only runs while q is None (nothing held)
                q = pool.alloc_page()  # graftlint: disable=resource_lifecycle
                if q is None:
                    # preemption may release the OTHER reference, making the
                    # copy unnecessary — the while re-checks
                    if not self.preempt_youngest(excluding=slot):
                        raise RuntimeError(
                            "page pool exhausted during copy-on-write — "
                            "engine misconfigured (max_len vs page pool)")
                    continue
                try:
                    self._copy_page(p, q)
                except BaseException:
                    pool.unref_page(q)   # unwritten copy frees cleanly
                    raise
                pool.cache_cow_copies += 1
                if self._m is not None:
                    self._m.cow.inc()
                pool.page_ref[p] -= 1
                self.slot_tables[slot, j] = q
                if j == int(self.n_alloc[slot]) - 1:
                    self.slot_tables[slot, j + 1:] = q   # repoint padding
                p = q

    def register_pages(self, slot, r):
        """Hash-register every completed full prompt page of this slot so
        later requests can hit it. First registration wins; a page whose
        content another physical page already serves stays private."""
        for j in range(int(self.lens[slot]) // self.page):
            self.pool.register(int(self.slot_tables[slot, j]),
                               r.cache_keys[j])

    def admit(self):
        pool = self.pool
        for slot in range(self.max_batch):
            if self.slots[slot] is not None or not self.waiting:
                continue
            r = self.waiting[0]
            # on-demand paging: reserve only the PROMPT's pages; decode
            # grows page-by-page (cf. the r3 engine's worst-case
            # prompt+max_new reservation, which gave paging no benefit)
            need = math.ceil(len(r.prompt) / self.page)
            keys = self.page_keys(r.prompt) if self.prefix_cache else []
            # the longest servable key prefix, walked across BOTH device
            # tiers: (key, page) for a resident HBM page, (key, None) for a
            # spilled chain entry to restore — a chain may interleave them
            # (restored pages re-evicted while later pages stayed resident)
            plan = []
            for key in keys:
                p = pool.lookup(key)
                if p is not None:
                    plan.append((key, p))
                elif pool.host is not None and key in pool.host \
                        and self._restore_chain is not None:
                    plan.append((key, None))
                else:
                    break
            n_dev = sum(1 for _, p in plan if p is not None)
            # pages admission must newly claim; hit pages sitting in the LRU
            # are about to be re-referenced, so they are NOT allocatable.
            # Host restores allocate from the same free/LRU budget as fresh
            # pages, so they count as claims here too.
            fresh = need - n_dev
            avail = pool.n_available(
                reserved_lru=sum(1 for _, p in plan
                                 if p is not None and p in pool.lru))
            if avail < fresh:
                break
            self.waiting.popleft()
            # ref HBM hits BEFORE allocating/restoring so eviction can't
            # take them out from under the plan.  RL102 can't follow the
            # branch-aware rollbacks: the short-restore path unrefs past
            # the gap below, and the alloc-fail path unrefs everything
            for _, p in plan:
                if p is not None:
                    pool.ref_page(p)  # graftlint: disable=resource_lifecycle
            # bring spilled runs back on-device in plan order; a short
            # restore truncates the usable cached prefix at the first gap
            pages, n_restored, usable, i = [], 0, len(plan), 0
            while i < usable:
                key, p = plan[i]
                if p is not None:
                    pages.append(p)
                    i += 1
                    continue
                run = []
                while i + len(run) < len(plan) \
                        and plan[i + len(run)][1] is None:
                    run.append(plan[i + len(run)][0])
                t0 = time.perf_counter()
                got = self._restore_chain(run)
                if r.trace_id is not None:
                    _flight.record("spill_restore", rid=r.rid,
                                   trace_id=r.trace_id,
                                   dur=time.perf_counter() - t0,
                                   asked=len(run), restored=len(got))
                pages.extend(got)
                n_restored += len(got)
                if len(got) < len(run):
                    usable = i + len(got)
                    # HBM hits past the gap are unreachable without it —
                    # drop the references taken above
                    for _, q in plan[usable:]:
                        if q is not None:
                            pool.unref_page(q)
                    break
                i += len(run)
            cached = len(pages)
            aborted = False
            for _ in range(need - cached):
                p = pool.alloc_page()
                if p is None:
                    # allocation failed mid-admission (injected fault, or a
                    # racing claim): roll the claimed pages back and requeue
                    # the request at the front — never a half-built table.
                    # Restored pages are content-registered, so unref parks
                    # them in the LRU with their contents intact.
                    for q in pages:
                        pool.unref_page(q)
                    self.waiting.appendleft(r)
                    aborted = True
                    break
                pages.append(p)
            if aborted:
                break
            self.slot_tables[slot, :need] = pages
            self.slot_tables[slot, need:] = pages[-1]
            self.n_alloc[slot] = need
            # skip prefill over fully-cached pages. At least the prompt's
            # FINAL token always re-prefills: its logits sample the first
            # output token (a 100%-cached prompt therefore re-enters its
            # last shared page, which is the copy-on-write path).
            skip = min(cached * self.page, len(r.prompt) - 1)
            pool.record_admission(cached, len(keys) - cached,
                                  n_host=n_restored)
            r.cache_keys = keys
            r.cached_tokens = skip
            r.pos = skip
            self.lens[slot] = skip
            r.slot = slot
            r.status = RequestStatus.RUNNING
            r.admit_seq = self._admit_seq
            self._admit_seq += 1
            self.slots[slot] = r

    def release(self, slot, status=None, error=None):
        """Free the slot's pages through the refcounts; ``status`` None is
        the requeue path (preemption — the request is NOT finalized), any
        terminal status finalizes the request."""
        r = self.slots[slot]
        for p in self.slot_tables[slot, :int(self.n_alloc[slot])]:
            self.pool.unref_page(int(p))
        self.slots[slot] = None
        self.lens[slot] = 0
        self.n_alloc[slot] = 0
        if status is not None:
            self.finalize(r, status, error=error)

    def preempt_youngest(self, excluding):
        """Free the youngest slot's pages, requeueing it for recompute
        (prompt := prompt + generated so far). Returns True if one was
        preempted."""
        victims = [(r.admit_seq, s) for s, r in enumerate(self.slots)
                   if r is not None and s != excluding]
        if not victims:
            return False
        _, slot = max(victims)
        r = self.slots[slot]
        # recompute prompt = ORIGINAL prompt + everything generated so far —
        # folding the current (possibly already-folded) prompt would
        # duplicate earlier output on a second preemption
        r.prompt = r.prompt0 + r.out
        if self.prefix_cache and self.pool.host is not None:
            # with a spill tier attached, content-register the victim's
            # completed pages under the FOLDED prompt's chain keys before
            # releasing: release then parks them in the LRU (spillable)
            # instead of freeing them, so preemption degrades to a copy
            # rather than a recompute when the victim re-admits
            keys = self.page_keys(r.prompt)
            for j in range(min(int(self.lens[slot]) // self.page,
                               len(keys))):
                self.pool.register(int(self.slot_tables[slot, j]), keys[j])
        self.release(slot, status=None)
        r.slot = None
        r.status = RequestStatus.QUEUED
        self.waiting.appendleft(r)
        self.preemptions += 1
        if self._m is not None:
            self._m.preempt.inc()
        return True

    def ensure_page(self, slot, ahead=1):
        """Grow slot's page table to cover `ahead` more tokens; preempt the
        youngest other slot if the pool is dry."""
        needed = (int(self.lens[slot]) + ahead + self.page - 1) // self.page
        while int(self.n_alloc[slot]) < needed:
            # RL102 sees preempt_youngest between alloc and the slot-table
            # store, but it only runs while p is None (nothing held)
            p = self.pool.alloc_page()  # graftlint: disable=resource_lifecycle
            if p is None:
                if not self.preempt_youngest(excluding=slot):
                    raise RuntimeError(
                        "page pool exhausted with a single slot — engine "
                        "misconfigured (max_len vs page pool)")
                continue
            na = int(self.n_alloc[slot])
            self.slot_tables[slot, na] = p
            self.slot_tables[slot, na + 1:] = p
            self.n_alloc[slot] = na + 1

    def truncate_pages(self, slot):
        """Free pages past ceil(lens/page) back to the pool — the rollback
        half of speculative decoding. Safe by construction: pages past the
        prompt are always privately allocated (refcount 1) and never
        registered in the prefix index, so a partially-filled page is
        truncated, never shared; the stale KV beyond lens is unreachable
        because attention masks by context length."""
        lens = int(self.lens[slot])
        needed = max(1, (lens + self.page - 1) // self.page)
        na = int(self.n_alloc[slot])
        if na <= needed:
            return
        for j in range(needed, na):
            self.pool.unref_page(int(self.slot_tables[slot, j]))
        self.slot_tables[slot, needed:] = self.slot_tables[slot, needed - 1]
        self.n_alloc[slot] = needed

    def emit(self, slot, token):
        """Record one generated token; release the slot when finished."""
        r = self.slots[slot]
        r.out.append(int(token))
        if self._m is not None:
            self._m.tokens.inc()
        if r.ttft is None:
            r.ttft = time.perf_counter() - r.t_submit
            if self._m is not None:
                self._m.ttft.observe(r.ttft)
            if r.trace_id is not None:
                _flight.record("first_token", rid=r.rid,
                               trace_id=r.trace_id, ttft=r.ttft)
        hit_eos = (r.eos is not None and r.out[-1] == r.eos)
        if (len(r.out) >= r.max_new or hit_eos
                or int(self.lens[slot]) >= self.max_len):
            self.release(slot, RequestStatus.EOS if hit_eos
                         else RequestStatus.FINISHED)

    # ------------------------------------------------------- disaggregation
    def detach(self, slot):
        """Lift the slot's request out WITHOUT dropping its page references
        — ownership of the refcounts moves to the caller (the KV handoff
        queue).  Returns ``(request, pages, n_tokens)`` where ``pages`` are
        the slot's allocated physical pages in table order and ``n_tokens``
        the cached length they cover."""
        r = self.slots[slot]
        pages = [int(p) for p in
                 self.slot_tables[slot, :int(self.n_alloc[slot])]]
        n_tokens = int(self.lens[slot])
        self.slots[slot] = None
        self.lens[slot] = 0
        self.n_alloc[slot] = 0
        r.slot = None
        return r, pages, n_tokens

    def free_slot(self):
        """Index of an empty slot, or None."""
        for slot in range(self.max_batch):
            if self.slots[slot] is None:
                return slot
        return None

    def admit_prefilled(self, r, pages, n_tokens):
        """Seat a request whose KV pages were written elsewhere (the
        receive half of a prefill→decode handoff).  ``pages`` must already
        carry this scheduler's pool references (the caller allocated them);
        ``r.pos`` must equal ``len(r.prompt)`` so the step loop never
        re-prefills.  Returns the slot, or None when the batch is full.

        Blocks arriving from ANOTHER process (the cross-host handoff) are
        validated here — the one choke point both the local and remote
        paths share — so a malformed transfer fails loudly instead of
        seating a slot whose lengths and tables disagree."""
        if not pages:
            raise ValueError(
                f"admit_prefilled(rid={r.rid}): no pages — a prefilled "
                "request owns at least one KV page")
        n_tokens = int(n_tokens)
        if not 0 < n_tokens <= len(pages) * self.page:
            raise ValueError(
                f"admit_prefilled(rid={r.rid}): n_tokens={n_tokens} does "
                f"not fit {len(pages)} pages of {self.page} tokens")
        if r.pos != len(r.prompt):
            raise ValueError(
                f"admit_prefilled(rid={r.rid}): pos={r.pos} != prompt len "
                f"{len(r.prompt)} — request was not fully prefilled")
        slot = self.free_slot()
        if slot is None:
            return None
        need = len(pages)
        self.slot_tables[slot, :need] = pages
        self.slot_tables[slot, need:] = pages[-1]
        self.n_alloc[slot] = need
        self.lens[slot] = n_tokens
        r.slot = slot
        r.status = RequestStatus.RUNNING
        r.admit_seq = self._admit_seq
        self._admit_seq += 1
        self.slots[slot] = r
        return slot

    # ----------------------------------------------------------------- misc
    def lookup(self, rid):
        """The live or terminal :class:`Request` for ``rid`` wherever it
        is — waiting, in a slot, or finished.  KeyError when unknown."""
        for r in self.waiting:
            if r.rid == rid:
                return r
        for r in self.slots:
            if r is not None and r.rid == rid:
                return r
        return self.finished[rid]

    def fail_all(self, error):
        """Finalize EVERY live request (waiting and running) as FAILED with
        ``error`` recorded — the front door calls this when a replica's
        step loop dies, so inflight requests end with a typed terminal
        status instead of hanging their streams forever."""
        while self.waiting:
            self.finalize(self.waiting.popleft(), RequestStatus.FAILED,
                          error=error)
        for slot, r in enumerate(self.slots):
            if r is not None:
                self.release(slot, RequestStatus.FAILED, error=error)

    def expected_refs(self, n_pages):
        """Per-page reference counts implied by the slot tables — the audit
        baseline; the caller adds any handoff holds before
        :meth:`~.pages.PagePool.audit`."""
        expected = np.zeros(n_pages, np.int64)
        for slot, r in enumerate(self.slots):
            if r is None:
                continue
            for j in range(int(self.n_alloc[slot])):
                expected[int(self.slot_tables[slot, j])] += 1
        return expected
