"""Paged-KV accounting: the host half of the engine's KV memory manager.

:class:`PagePool` owns every *host-side* page structure — the free list, the
per-page refcounts, the chain-hash prefix index, and the reclaimable LRU —
while the device arrays the pages index into live in
:class:`~.runner.ModelRunner`.  The split is the engine-core refactor's
contract: the pool never touches a device buffer (copy-on-write's device
copy is a callable injected by the engine — and so is the spill tier's
device→host gather, ``spill_page``), and the runner never sees a refcount.

:class:`HostPageStore` is the host-RAM spill tier behind the LRU: when a
host store is attached, LRU eviction copies the page's contents to host
RAM (keyed by the same chain key) instead of discarding them, and the
scheduler's admission walk restores spilled chains back into fresh device
pages instead of re-prefilling.  The store has its own byte budget and LRU;
entries are immutable host copies that no slot table ever references, so
its refcount discipline reduces to exact byte accounting (checked by
:meth:`HostPageStore.audit`, folded into :meth:`PagePool.audit`).

Invariants (checked by :meth:`audit`):

- a page's refcount equals the number of slot-table references to it (plus
  any in-flight handoff references the caller declares),
- free and LRU-parked pages carry refcount 0 and never overlap,
- no page leaks (refcount 0 yet neither free nor parked),
- LRU pages are content-registered and the prefix key index is symmetric,
- the host tier's byte ledger matches its entries and respects its budget.
"""
from __future__ import annotations

from collections import OrderedDict, deque

import numpy as np

from ...testing.faults import FAULTS as _faults

__all__ = ["HostPageStore", "PagePool"]


class HostPageStore:
    """Byte-budgeted host-RAM tier for spilled KV pages.

    One entry per chain key: the page's full contents as a tuple of host
    numpy arrays (one ``[L, 1, page, ...]`` array per cache component),
    copied off-device with the non-blocking snapshot idiom.  Entries are
    LRU-ordered; ``put`` evicts oldest-first until the new entry fits and
    refuses entries larger than the whole budget.  ``on_evict(key)`` fires
    for every evicted entry so the pool can drop the chain key from the
    frontend router's mirror when no device copy remains."""

    def __init__(self, budget_bytes, on_evict=None):
        self.budget = int(budget_bytes)
        self.entries: OrderedDict = OrderedDict()   # chain key -> host block
        self.bytes_used = 0
        self.on_evict = on_evict
        self.spills = 0            # entries accepted by put()
        self.spill_bytes = 0       # bytes accepted by put()
        self.evictions = 0         # entries evicted to fit newer spills

    def __contains__(self, key):
        return key in self.entries

    def __len__(self):
        return len(self.entries)

    @staticmethod
    def block_bytes(block):
        return sum(int(a.nbytes) for a in block)

    def get(self, key):
        """The host block for ``key`` (LRU-refreshed), or None."""
        block = self.entries.get(key)
        if block is not None:
            self.entries.move_to_end(key)
        return block

    def touch(self, key):
        """LRU-refresh ``key`` without reading it; True when present."""
        if key in self.entries:
            self.entries.move_to_end(key)
            return True
        return False

    def put(self, key, block):
        """Admit one spilled page; True when the store holds it afterwards.
        Oldest entries are evicted until the newcomer fits; a block larger
        than the whole budget is refused (the caller falls back to plain
        eviction — recompute)."""
        if key in self.entries:
            self.entries.move_to_end(key)
            return True
        nbytes = self.block_bytes(block)
        if nbytes > self.budget:
            return False
        while self.bytes_used + nbytes > self.budget and self.entries:
            k, old = self.entries.popitem(last=False)
            self.bytes_used -= self.block_bytes(old)
            self.evictions += 1
            if self.on_evict is not None:
                self.on_evict(k)
        self.entries[key] = block
        self.bytes_used += nbytes
        self.spills += 1
        self.spill_bytes += nbytes
        return True

    def pop(self, key):
        block = self.entries.pop(key, None)
        if block is not None:
            self.bytes_used -= self.block_bytes(block)
        return block

    def keys(self):
        return list(self.entries)

    def headroom_pages(self, bytes_per_page):
        """How many more pages the remaining budget could absorb."""
        if bytes_per_page <= 0:
            return 0
        return max(0, (self.budget - self.bytes_used) // int(bytes_per_page))

    def audit(self):
        """Byte-ledger invariants; returns problem strings (empty = clean)."""
        problems = []
        actual = sum(self.block_bytes(b) for b in self.entries.values())
        if actual != self.bytes_used:
            problems.append(f"host tier byte ledger {self.bytes_used} != "
                            f"{actual} actual entry bytes")
        if self.bytes_used > self.budget:
            problems.append(f"host tier over budget: {self.bytes_used} > "
                            f"{self.budget}")
        return problems


class PagePool:
    """Refcounted page allocator with an optional chain-hash prefix index.

    ``n_pages`` INCLUDES the trash page (``n_pages - 1``), which is never
    allocated — it absorbs the masked-out writes of inactive batch rows.
    ``metrics`` is an optional object carrying bound registry counters
    (``hits`` / ``misses`` / ``evictions`` / ``cow``); every metric touch is
    guarded so the pool works metric-less (the disagg prefill/decode slices
    each bind their own engine's metrics)."""

    def __init__(self, n_pages, prefix_cache=False, metrics=None):
        self.n_pages = int(n_pages)
        self.trash_page = self.n_pages - 1
        self.free_pages = deque(range(self.n_pages - 1))
        self.page_ref = np.zeros(self.n_pages, np.int64)
        self.prefix_cache = bool(prefix_cache)
        # optional (event, chain_key) callback — the frontend router
        # subscribes here to mirror this engine's radix index ("register" on
        # page registration, "evict" on LRU reclaim) into its per-replica
        # affinity index.  Called from inside step(); must be cheap and
        # must not raise.
        self.cache_event_listener = None
        self.page_key: dict = {}          # physical page -> chain key
        self.key_page: dict = {}          # chain key -> physical page
        self.lru: OrderedDict = OrderedDict()  # cached, refcount==0 pages
        self.cache_hits = 0                # pages served from cache (admit)
        self.cache_misses = 0              # full prompt pages not cached
        self.cache_evictions = 0           # cached pages reclaimed from LRU
        self.cache_cow_copies = 0          # copy-on-write page copies
        self._m = metrics
        # ---- optional host-RAM spill tier (attach_host) -------------------
        self.host: HostPageStore | None = None
        # engine-injected device→host gather: spill_page(p) -> host block or
        # None on failure (same injection contract as the CoW copy_page — the
        # pool never touches device buffers itself)
        self.spill_page = None
        self.host_hits = 0                 # admission pages restored from host
        self._host_page_bytes = 0

    # ------------------------------------------------------------- refcounts
    def ref_page(self, p):
        self.page_ref[p] += 1
        self.lru.pop(p, None)         # referenced again: not reclaimable

    def unref_page(self, p):
        self.page_ref[p] -= 1
        if self.page_ref[p] > 0:
            return
        if p in self.page_key:        # content cached: park reclaimable
            self.lru[p] = None
            self.lru.move_to_end(p)
        else:
            self.free_pages.append(p)

    def alloc_page(self):
        """A writable page with refcount 1: free list first, then LRU
        eviction of the oldest cached-but-unreferenced page. Returns None
        when both are dry (the caller preempts — last resort)."""
        if _faults.active and _faults.fire("serving.page_alloc") is not None:
            return None               # injected allocation failure (dry pool)
        if self.free_pages:
            p = self.free_pages.popleft()
        elif self.lru:
            p, _ = self.lru.popitem(last=False)
            key = self.page_key.pop(p)
            self.key_page.pop(key, None)
            self.cache_evictions += 1
            if self._m is not None:
                self._m.evictions.inc()
            self._spill_or_evict(p, key)
        else:
            return None
        self.page_ref[p] = 1
        return p

    def _spill_or_evict(self, p, key):
        """Demote an LRU-reclaimed page: into the host tier when one is
        attached (the chain key survives, event "spill"), else a plain
        eviction (event "evict").  Spill failure degrades to eviction —
        correctness never depends on the copy."""
        if self.host is not None:
            if key in self.host:
                self.host.touch(key)      # already spilled: HBM copy was a
                spilled = True            # restore — the host copy stands
            else:
                blk = self.spill_page(p) if self.spill_page is not None \
                    else None
                spilled = blk is not None and self.host.put(key, blk)
            if spilled:
                if self.cache_event_listener is not None:
                    self.cache_event_listener("spill", key)
                return
        if self.cache_event_listener is not None:
            self.cache_event_listener("evict", key)

    # -------------------------------------------------------- host spill tier
    def attach_host(self, store: HostPageStore, bytes_per_page):
        """Wire the host-RAM tier in: LRU reclaims spill through it and its
        own evictions notify the cache-event listener (the chain is then gone
        from every tier of this replica)."""
        self.host = store
        self._host_page_bytes = int(bytes_per_page)
        store.on_evict = self._host_evicted

    def _host_evicted(self, key):
        # the host tier aged a chain key out; only announce the loss when no
        # device page still serves that key (restore re-registered it in HBM)
        if key not in self.key_page and self.cache_event_listener is not None:
            self.cache_event_listener("evict", key)

    def host_headroom_pages(self):
        """Pages the host tier could still absorb without evicting — the
        shed watermark and SLO admission count these as reclaimable-without-
        loss headroom."""
        if self.host is None or self._host_page_bytes <= 0:
            return 0
        return min(self.host.headroom_pages(self._host_page_bytes),
                   self.n_usable)

    # ----------------------------------------------------------- prefix index
    def lookup(self, key):
        """Physical page currently serving ``key``'s content, or None."""
        return self.key_page.get(key)

    def register(self, p, key):
        """Content-register page ``p`` under chain ``key``.  First
        registration wins; a page whose content another physical page
        already serves stays private.  Returns True when registered."""
        if p in self.page_key or key in self.key_page:
            return False
        self.page_key[p] = key
        self.key_page[key] = p
        if self.cache_event_listener is not None:
            self.cache_event_listener("register", key)
        return True

    def record_admission(self, n_hits, n_misses, n_host=0):
        """Admission-time hit/miss accounting (pages, not tokens).
        ``n_host`` is the subset of ``n_hits`` served by restoring spilled
        pages from the host tier rather than from resident HBM pages."""
        self.cache_hits += n_hits
        self.cache_misses += n_misses
        self.host_hits += n_host
        if self._m is not None:
            self._m.hits.inc(n_hits)
            self._m.misses.inc(n_misses)
            if n_hits - n_host:
                self._m.tier_hits_hbm.inc(n_hits - n_host)
            if n_host:
                self._m.tier_hits_host.inc(n_host)

    # ------------------------------------------------------------------ state
    @property
    def n_usable(self):
        """Pages the budget covers (the trash page excluded)."""
        return self.n_pages - 1

    def n_available(self, reserved_lru=0, host_headroom=False):
        """Pages admission could newly claim: free + reclaimable, minus LRU
        pages the caller is about to re-reference (cache hits parked in the
        LRU are NOT allocatable — they are being claimed as hits).  With
        ``host_headroom=True`` (shed-watermark accounting only), LRU pages
        the host tier could absorb count as reclaimable-without-loss."""
        avail = len(self.free_pages) + len(self.lru) - reserved_lru
        if host_headroom:
            avail += min(self.host_headroom_pages(), len(self.lru))
        return avail

    # ------------------------------------------------------------------ audit
    def audit(self, expected_refs):
        """Cross-check every page-accounting structure against the others;
        returns a list of problem strings (empty means clean).
        ``expected_refs`` is the caller-computed per-page reference count
        (slot-table references plus any in-flight handoff holds)."""
        problems = []
        free = [int(p) for p in self.free_pages]
        free_set = set(free)
        if len(free_set) != len(free):
            problems.append("free list holds duplicate pages")
        lru_set = {int(p) for p in self.lru}
        both = free_set & lru_set
        if both:
            problems.append(f"pages both free and LRU-parked: {sorted(both)}")
        for p in range(self.n_pages - 1):            # trash page excluded
            refs, exp = int(self.page_ref[p]), int(expected_refs[p])
            if refs != exp:
                problems.append(f"page {p}: refcount {refs} != "
                                f"{exp} slot-table references")
            if refs == 0 and p not in free_set and p not in lru_set:
                problems.append(f"page {p}: leaked "
                                "(refcount 0, neither free nor LRU-parked)")
            if refs > 0 and (p in free_set or p in lru_set):
                problems.append(f"page {p}: referenced but on the "
                                "free/LRU list")
        for p in lru_set:
            if p not in self.page_key:
                problems.append(f"page {p}: LRU-parked but not "
                                "content-registered")
        for p, key in self.page_key.items():
            if self.key_page.get(key) != p:
                problems.append(f"page {p}: page->key->page asymmetric")
        for key, p in self.key_page.items():
            if self.page_key.get(p) != key:
                problems.append(f"page {p}: key->page->key asymmetric")
        if self.host is not None:
            problems.extend(self.host.audit())
        return problems
