"""Paged-KV accounting: the host half of the engine's KV memory manager.

:class:`PagePool` owns every *host-side* page structure — the free list, the
per-page refcounts, the chain-hash prefix index, and the reclaimable LRU —
while the device arrays the pages index into live in
:class:`~.runner.ModelRunner`.  The split is the engine-core refactor's
contract: the pool never touches a device buffer (copy-on-write's device
copy is a callable injected by the engine), and the runner never sees a
refcount.

Invariants (checked by :meth:`audit`):

- a page's refcount equals the number of slot-table references to it (plus
  any in-flight handoff references the caller declares),
- free and LRU-parked pages carry refcount 0 and never overlap,
- no page leaks (refcount 0 yet neither free nor parked),
- LRU pages are content-registered and the prefix key index is symmetric.
"""
from __future__ import annotations

from collections import OrderedDict, deque

import numpy as np

from ...testing.faults import FAULTS as _faults

__all__ = ["PagePool"]


class PagePool:
    """Refcounted page allocator with an optional chain-hash prefix index.

    ``n_pages`` INCLUDES the trash page (``n_pages - 1``), which is never
    allocated — it absorbs the masked-out writes of inactive batch rows.
    ``metrics`` is an optional object carrying bound registry counters
    (``hits`` / ``misses`` / ``evictions`` / ``cow``); every metric touch is
    guarded so the pool works metric-less (the disagg prefill/decode slices
    each bind their own engine's metrics)."""

    def __init__(self, n_pages, prefix_cache=False, metrics=None):
        self.n_pages = int(n_pages)
        self.trash_page = self.n_pages - 1
        self.free_pages = deque(range(self.n_pages - 1))
        self.page_ref = np.zeros(self.n_pages, np.int64)
        self.prefix_cache = bool(prefix_cache)
        # optional (event, chain_key) callback — the frontend router
        # subscribes here to mirror this engine's radix index ("register" on
        # page registration, "evict" on LRU reclaim) into its per-replica
        # affinity index.  Called from inside step(); must be cheap and
        # must not raise.
        self.cache_event_listener = None
        self.page_key: dict = {}          # physical page -> chain key
        self.key_page: dict = {}          # chain key -> physical page
        self.lru: OrderedDict = OrderedDict()  # cached, refcount==0 pages
        self.cache_hits = 0                # pages served from cache (admit)
        self.cache_misses = 0              # full prompt pages not cached
        self.cache_evictions = 0           # cached pages reclaimed from LRU
        self.cache_cow_copies = 0          # copy-on-write page copies
        self._m = metrics

    # ------------------------------------------------------------- refcounts
    def ref_page(self, p):
        self.page_ref[p] += 1
        self.lru.pop(p, None)         # referenced again: not reclaimable

    def unref_page(self, p):
        self.page_ref[p] -= 1
        if self.page_ref[p] > 0:
            return
        if p in self.page_key:        # content cached: park reclaimable
            self.lru[p] = None
            self.lru.move_to_end(p)
        else:
            self.free_pages.append(p)

    def alloc_page(self):
        """A writable page with refcount 1: free list first, then LRU
        eviction of the oldest cached-but-unreferenced page. Returns None
        when both are dry (the caller preempts — last resort)."""
        if _faults.active and _faults.fire("serving.page_alloc") is not None:
            return None               # injected allocation failure (dry pool)
        if self.free_pages:
            p = self.free_pages.popleft()
        elif self.lru:
            p, _ = self.lru.popitem(last=False)
            key = self.page_key.pop(p)
            self.key_page.pop(key, None)
            self.cache_evictions += 1
            if self._m is not None:
                self._m.evictions.inc()
            if self.cache_event_listener is not None:
                self.cache_event_listener("evict", key)
        else:
            return None
        self.page_ref[p] = 1
        return p

    # ----------------------------------------------------------- prefix index
    def lookup(self, key):
        """Physical page currently serving ``key``'s content, or None."""
        return self.key_page.get(key)

    def register(self, p, key):
        """Content-register page ``p`` under chain ``key``.  First
        registration wins; a page whose content another physical page
        already serves stays private.  Returns True when registered."""
        if p in self.page_key or key in self.key_page:
            return False
        self.page_key[p] = key
        self.key_page[key] = p
        if self.cache_event_listener is not None:
            self.cache_event_listener("register", key)
        return True

    def record_admission(self, n_hits, n_misses):
        """Admission-time hit/miss accounting (pages, not tokens)."""
        self.cache_hits += n_hits
        self.cache_misses += n_misses
        if self._m is not None:
            self._m.hits.inc(n_hits)
            self._m.misses.inc(n_misses)

    # ------------------------------------------------------------------ state
    @property
    def n_usable(self):
        """Pages the budget covers (the trash page excluded)."""
        return self.n_pages - 1

    def n_available(self, reserved_lru=0):
        """Pages admission could newly claim: free + reclaimable, minus LRU
        pages the caller is about to re-reference (cache hits parked in the
        LRU are NOT allocatable — they are being claimed as hits)."""
        return len(self.free_pages) + len(self.lru) - reserved_lru

    # ------------------------------------------------------------------ audit
    def audit(self, expected_refs):
        """Cross-check every page-accounting structure against the others;
        returns a list of problem strings (empty means clean).
        ``expected_refs`` is the caller-computed per-page reference count
        (slot-table references plus any in-flight handoff holds)."""
        problems = []
        free = [int(p) for p in self.free_pages]
        free_set = set(free)
        if len(free_set) != len(free):
            problems.append("free list holds duplicate pages")
        lru_set = {int(p) for p in self.lru}
        both = free_set & lru_set
        if both:
            problems.append(f"pages both free and LRU-parked: {sorted(both)}")
        for p in range(self.n_pages - 1):            # trash page excluded
            refs, exp = int(self.page_ref[p]), int(expected_refs[p])
            if refs != exp:
                problems.append(f"page {p}: refcount {refs} != "
                                f"{exp} slot-table references")
            if refs == 0 and p not in free_set and p not in lru_set:
                problems.append(f"page {p}: leaked "
                                "(refcount 0, neither free nor LRU-parked)")
            if refs > 0 and (p in free_set or p in lru_set):
                problems.append(f"page {p}: referenced but on the "
                                "free/LRU list")
        for p in lru_set:
            if p not in self.page_key:
                problems.append(f"page {p}: LRU-parked but not "
                                "content-registered")
        for p, key in self.page_key.items():
            if self.key_page.get(key) != p:
                problems.append(f"page {p}: page->key->page asymmetric")
        for key, p in self.key_page.items():
            if self.page_key.get(p) != key:
                problems.append(f"page {p}: key->page->key asymmetric")
        return problems
