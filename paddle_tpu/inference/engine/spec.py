"""Speculative decoding: drafting, verification orchestration, auto-fit.

Verification itself is a :class:`~.runner.ModelRunner` program
(``run_verify``); this module holds everything speculative around it —
the draft side (config + the self-drafting n-gram / small-draft-model
proposers behind one ``propose(tokens, k)`` interface) and the
:class:`_SpecOrchestration` mixin :class:`~.core.LLMEngine` inherits
(propose → single multi-query verify dispatch → accept-longest-prefix →
paged-KV rollback, plus the adaptive draft-length cost fit).
"""
from __future__ import annotations

import time

import numpy as np

from ... import observability as _obs
from ...testing.faults import FAULTS as _faults

__all__ = ["SpecConfig"]


def ceil_pow2(n):
    return 1 << max(0, int(n) - 1).bit_length() if n > 1 else 1


class SpecConfig:
    """Speculative-decoding knob (``LLMEngine(spec_decode=SpecConfig())``).

    max_draft: most draft tokens proposed per request per verify step.
    ngram_max / ngram_min: window bounds for the self-drafting n-gram
        proposer — the request's current n-token suffix (longest n first)
        is matched against its own earlier prompt+generated tokens, and
        the tokens that followed the most recent match become the draft.
        Free (no extra weights); wins on repetitive structure (code,
        retrieved context, templated text).
    draft_model: optional small LlamaForCausalLM replacing the n-gram
        proposer — greedy continuation of the request's token history.
    adaptive: learn the verify dispatch's cost curve t(rows) = RTT+rows*c
        (separately from the decode-block auto-fit: a verify step consumes
        a VARIABLE number of tokens) and pick the draft length maximizing
        expected accepted tokens per second under the observed acceptance
        rate; False always proposes max_draft."""

    def __init__(self, max_draft=4, ngram_max=3, ngram_min=1,
                 draft_model=None, adaptive=True):
        if int(max_draft) < 1:
            raise ValueError("max_draft must be >= 1")
        if int(ngram_min) < 1 or int(ngram_max) < int(ngram_min):
            raise ValueError("need 1 <= ngram_min <= ngram_max")
        self.max_draft = int(max_draft)
        self.ngram_max = int(ngram_max)
        self.ngram_min = int(ngram_min)
        self.draft_model = draft_model
        self.adaptive = bool(adaptive)

    def make_proposer(self):
        return (_DraftModelProposer(self.draft_model)
                if self.draft_model is not None else _NgramProposer(self))


class _NgramProposer:
    """Self-drafting proposer: find the most recent earlier occurrence of
    the sequence's current suffix (longest n in [ngram_min, ngram_max]
    wins) and propose the tokens that followed that occurrence."""

    def __init__(self, cfg):
        self.cfg = cfg

    def propose(self, tokens, k):
        n_tok = len(tokens)
        hi = min(self.cfg.ngram_max, n_tok - 1)
        for n in range(hi, self.cfg.ngram_min - 1, -1):
            suffix = tokens[n_tok - n:]
            for i in range(n_tok - n - 1, -1, -1):
                if tokens[i:i + n] == suffix:
                    cont = tokens[i + n:i + n + k]
                    if cont:
                        return list(cont)
        return []


class _DraftModelProposer:
    """Draft-model proposer: greedy continuation from a small model. The
    draft recomputes from the full token history each call (no persistent
    draft KV) — drafts are short and the draft model is small, so clarity
    beats cache bookkeeping here."""

    def __init__(self, model):
        self.model = model

    def propose(self, tokens, k):
        from ... import to_tensor
        ids = to_tensor(np.asarray([tokens], np.int64))
        out = self.model.generate(ids, max_new_tokens=k, do_sample=False)
        seq = np.asarray(out._data).reshape(-1)
        return [int(t) for t in seq[len(tokens):]]


class _SpecOrchestration:
    """Speculative-decode orchestration mixed into
    :class:`~.core.LLMEngine` — every attribute referenced here
    (``self.runner`` / ``self.sched`` / ``self._m`` / the ``spec_*``
    counters / ``self._spec`` / ``self._proposer``) is constructed by the
    engine's ``__init__``; the mixin imports no sibling module, so the
    package layering guard stays acyclic."""

    def _propose_drafts(self, live):
        """Draft continuation tokens per live slot, capped so that drafts+1
        emitted tokens can neither exceed the request's remaining budget nor
        run past max_len."""
        props = {}
        target = self._spec_draft_target()
        for slot, r in live:
            cap = min(target, r.max_new - len(r.out) - 1,
                      self.max_len - int(self.sched.lens[slot]) - 1)
            if cap < 1:
                props[slot] = []
                continue
            # full token history (prompt0+out survives preemption re-folds)
            props[slot] = self._proposer.propose(r.prompt0 + r.out, cap)[:cap]
        return props

    def _spec_step(self, live, props):
        """One speculative step: verify every live slot's pending token plus
        its drafts in a single multi-query dispatch, emit the accepted run,
        roll rejected pages back. Slots without a proposal ride along with
        one row (their pending token advances normally)."""
        sched = self.sched
        for slot, r in live:
            if sched.slots[slot] is not r:
                continue        # preempted by an earlier slot's growth
            sched.ensure_page(slot, ahead=len(props.get(slot, ())) + 1)
        live = [(s, r) for s, r in live if sched.slots[s] is r]
        if not live:
            return 0
        Kv = ceil_pow2(max(len(props.get(s, ())) + 1 for s, _ in live))
        tokens = np.zeros((self.max_batch, Kv), np.int32)
        n_rows = np.zeros((self.max_batch,), np.int32)
        greedy = np.ones((self.max_batch,), np.int32)
        temp = np.ones((self.max_batch,), np.float32)
        topp = np.ones((self.max_batch,), np.float32)
        topk = np.zeros((self.max_batch,), np.int32)
        seeds = np.zeros((self.max_batch,), np.int32)
        fold = np.zeros((self.max_batch,), np.int32)
        for slot, r in live:
            drafts = props.get(slot, [])
            n_rows[slot] = 1 + len(drafts)
            tokens[slot, 0] = r.out[-1]
            tokens[slot, 1:1 + len(drafts)] = drafts
            greedy[slot] = 0 if r.do_sample else 1
            temp[slot] = r.temperature
            topp[slot] = r.top_p
            topk[slot] = r.top_k
            seeds[slot] = self._next_seed(r)
            fold[slot] = 1 if r.seed is None else 0
        self._step_phase = ("verify", tuple(s for s, _ in live))
        _faults.maybe_fire("serving.step", rids=[r.rid for _, r in live],
                           phase="verify")
        compile_call = not self.runner.has_verify_program(Kv)
        self.spec_dispatches += 1
        self._m.verify.inc()
        t0 = time.perf_counter()
        with _obs.trace_span("serving.verify"):
            toks = self.runner.run_verify(
                Kv, tokens, sched.lens, sched.slot_tables, n_rows,
                greedy, temp, topp, topk, seeds, fold)       # [B, Kv]
        dt = time.perf_counter() - t0
        if self._spec.adaptive and not compile_call:
            self._record_verify_sample(Kv, dt)
        proposed = accepted = 0
        for slot, r in live:
            drafts = props.get(slot, [])
            n = len(drafts)
            t = toks[slot]
            # accept the longest draft prefix the target would have sampled
            # itself: draft j+1 (fed at row j+1) survives iff it equals the
            # token sampled from row j's logits
            a = 0
            while a < n and drafts[a] == int(t[a]):
                a += 1
            proposed += n
            accepted += a
            m = a + 1                                    # tokens to emit
            for j in range(m):
                if sched.slots[slot] is not r:
                    break        # eos / max_new released the slot mid-run
                sched.lens[slot] += 1
                sched.emit(slot, int(t[j]))
                self.spec_emitted += 1
            if sched.slots[slot] is r:
                # roll back KV pages provisioned for rejected drafts
                sched.truncate_pages(slot)
            if not compile_call and _obs.enabled():
                self._m.token_latency.observe(dt / m)
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self._m.spec_proposed.inc(proposed)
        self._m.spec_accepted.inc(accepted)
        if proposed:
            ratio = accepted / proposed
            self._m.spec_acceptance.observe(ratio)
            self._spec_accept_ema = (
                ratio if self._spec_accept_ema is None
                else 0.9 * self._spec_accept_ema + 0.1 * ratio)
        return len(live)

    def _record_verify_sample(self, rows, wall_dt):
        samples = self._spec_samples.setdefault(rows, [])
        samples.append(wall_dt)
        del samples[:-8]

    def _spec_draft_target(self):
        """Draft length maximizing expected emitted tokens per second,
        E(k) / t(rows(k)), from the verify step's OWN cost fit (decode
        blocks consume exactly k tokens; a verify step consumes a variable
        1..k+1, so it gets a separate t(rows) = RTT + rows*c model) and the
        acceptance-rate EMA: E(k) = 1 + a + a^2 + ... + a^k."""
        cfg = self._spec
        if not cfg.adaptive:
            return cfg.max_draft
        sampled = {kk: sorted(v)[len(v) // 2]
                   for kk, v in self._spec_samples.items() if v}
        if len(sampled) < 2:
            return cfg.max_draft      # not solvable yet: be optimistic
        ks = sorted(sampled)
        c, rtt = np.polyfit(np.asarray(ks, np.float64),
                            np.asarray([sampled[kk] for kk in ks],
                                       np.float64), 1)
        if c <= 0 or rtt < 0:
            return cfg.max_draft
        alpha = min(0.99, max(0.0, self._spec_accept_ema
                              if self._spec_accept_ema is not None else 0.5))
        best_k, best_rate = 1, -1.0
        for k in range(1, cfg.max_draft + 1):
            e = (k + 1 if alpha == 1.0
                 else (1 - alpha ** (k + 1)) / (1 - alpha))
            rate = e / (rtt + ceil_pow2(k + 1) * c)
            if rate > best_rate:
                best_rate, best_k = rate, k
        return best_k

    def spec_stats(self):
        """Always-on speculative-decoding counters (zero when the
        ``spec_decode`` knob is off). ``tokens_per_step`` is tokens emitted
        per VERIFY dispatch — the speculative speedup factor (> 1.0 means
        drafts are being accepted); the registry mirrors proposed/accepted
        as ``serving_spec_*_total`` plus the acceptance histogram."""
        return {
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "emitted": self.spec_emitted,
            "verify_dispatches": self.spec_dispatches,
            "acceptance_rate": (self.spec_accepted / self.spec_proposed
                                if self.spec_proposed else 0.0),
            "tokens_per_step": (self.spec_emitted / self.spec_dispatches
                                if self.spec_dispatches else 0.0),
            "draft_target": (self._spec_draft_target()
                             if self._spec is not None else 0),
        }
