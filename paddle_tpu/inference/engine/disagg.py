"""Disaggregated prefill/decode serving: an M:N pool of engine cores on
disjoint mesh slices with pipelined KV-page handoff between their pools.

The phase-separation argument (DistServe OSDI'24, Splitwise ISCA'24): in a
colocated engine every chunked prefill that lands in a step stalls ALL
co-resident decode slots — the step loop is prefill-first, so a long prompt
arriving mid-stream inflates every other request's inter-token latency.
:class:`DisaggEngine` runs PREFILL engines on their own mesh slices and
DECODE engines on others; each :meth:`step` always dispatches the decode
side and only additionally dispatches prefill chunks when the shared
handoff queue has room, so decode token cadence is never blocked behind a
prompt — even on a single device, where "slices" are just independent
buffer sets.  Prefill demand is bursty (Mooncake), so the pool is M:N: any
number of prefill engines (local, or remote worker processes — see below)
feed any number of decode engines through ONE bounded queue, and each
drained handoff picks the least-loaded decode engine at placement time.

The seam is the KV-page handoff: when a prompt finishes prefilling, the
prefill engine's ``prefill_sink`` detaches the request WITH its page
refcounts into the bounded queue.  With ``async_handoff`` (the default)
the transfer is *pipelined*: staging allocates destination pages and
dispatches the jitted gather + ``jax.device_put`` for handoff *k+1*
asynchronously, the decode engines run their step while the copy is in
flight, and the landing half (jitted scatter + ``admit_prefilled``) runs
at the top of the NEXT round, before that round's decode — the transfer
hides under decode compute instead of serializing with it (seating
latency matches the blocking hop, minus the stall), double-buffered
exactly like ``runner.restore_pages``.  ``async_handoff=False`` keeps the original
blocking hop (gather → device_put → scatter inline before the decode
step), which the bench uses as the 1:1-sync comparator.  Source pages are
released as soon as the gather is dispatched (the dispatched program owns
the data); content-registered prompt pages park in the prefill LRU, so
prefix-cache hits survive disaggregation.  A full queue back-pressures
admission: prefill engines stop stepping, their waiting queues grow, and
the ordinary ``max_waiting`` / page-pressure shedding applies.

Cross-host: a prefill engine living in a different worker process joins
the pool as a *remote prefill tier* (``remote_prefill=[...]``, duck-typed
— see ``frontend/disagg.py``): the pool submits prompts to it over the
worker RPC plane, and a finished prefill comes back as a serialized host
page block (the ``pull_pages``/``push_pages`` framing of the KV peer
tier) that lands through the same queue → stage → scatter pipeline, with
``jax.device_put`` of the host block replacing the device-to-device hop.

Fault surface: every handoff fires the ``serving.kv_handoff`` point
BEFORE any page is copied (ctx has ``rids`` and ``path`` —
``local``/``cross_host``), so transient faults retry idempotently under
the shared :class:`RetryPolicy`; a poisoned handoff quarantines ONLY that
request (terminal FAILED, pages released on every slice that held any).

Parity: greedy and fixed-seed requests are token-exact with a colocated
:class:`~.core.LLMEngine` regardless of pool shape, transfer pipelining,
or transport — the copied pages are bit-identical to what the decode
slice would have written (same program, same absolute RoPE positions;
int8 pages and scales copy verbatim), and per-request sampling seeds do
not depend on dispatch structure.  (Seedless sampling draws from a
per-engine global counter and is not parity-stable, exactly as with the
colocated prefix cache.)
"""
from __future__ import annotations

import time
from collections import deque

import numpy as np
import jax

from ... import observability as _obs
from ...observability import flight as _flight
from ...core.retry import RetryError, RetryPolicy, retry_call
from ...testing.faults import FAULTS as _faults
from .core import LLMEngine
from .metrics import _PoolMetrics
from .request import Request, RequestStatus

__all__ = ["DisaggEngine", "split_mesh"]

# local prefill engine i allocates rids in [i*STRIDE, (i+1)*STRIDE); remote
# tier t gets the namespace after the local engines — rids stay globally
# unique across the pool with zero translation, and the 1:1 default keeps
# the colocated engine's 0, 1, 2, ... sequence exactly
_RID_STRIDE = 1_000_000_000


def split_mesh(mesh, axis=None, sizes=None):
    """Split ``mesh`` along ``axis`` into submeshes that keep every axis
    name, so the engines' pp×mp shardings apply unchanged to each slice.

    Default (``sizes=None``): two even halves along ``axis`` (or the first
    axis with even size >= 2), returned as ``(prefill_mesh, decode_mesh)``.

    ``sizes=(a, b, ...)``: partition the axis into ``len(sizes)`` meshes of
    those extents (uneven and N-way splits — the slice sizing an M:N pool
    needs); the sizes must be positive and sum to the axis size exactly.
    """
    from jax.sharding import Mesh
    names = mesh.axis_names
    if axis is None:
        if sizes is not None:
            need = sum(int(s) for s in sizes)
            axis = next((n for n in names if mesh.shape[n] == need), None)
            if axis is None:
                raise ValueError(
                    f"no mesh axis of size {need} to split into sizes "
                    f"{tuple(sizes)} (shape {dict(mesh.shape)}); pass axis= "
                    "explicitly or fix the sizes")
        else:
            axis = next((n for n in names
                         if mesh.shape[n] >= 2 and mesh.shape[n] % 2 == 0),
                        None)
            if axis is None:
                raise ValueError(
                    f"no mesh axis with even size >= 2 to split (shape "
                    f"{dict(mesh.shape)}); pass prefill_mesh/decode_mesh "
                    "explicitly")
    if axis not in names:
        raise ValueError(
            f"mesh has no axis {axis!r} (axes: {list(names)})")
    size = int(mesh.shape[axis])
    if sizes is None:
        if size < 2 or size % 2:
            raise ValueError(
                f"axis {axis!r} has size {size}, which even halves cannot "
                f"split; pass sizes=, e.g. sizes=({size - 1}, 1)")
        sizes = (size // 2, size - size // 2)
    sizes = tuple(int(s) for s in sizes)
    if any(s <= 0 for s in sizes):
        raise ValueError(
            f"split_mesh sizes must be positive ints, got {sizes}")
    if sum(sizes) != size:
        raise ValueError(
            f"sizes {sizes} sum to {sum(sizes)} but axis {axis!r} has size "
            f"{size}; sizes must partition the axis exactly")
    ai = list(names).index(axis)
    devs = mesh.devices
    out, start = [], 0
    for s in sizes:
        sl = [slice(None)] * devs.ndim
        sl[ai] = slice(start, start + s)
        out.append(Mesh(devs[tuple(sl)], names))
        start += s
    return tuple(out)


class _TransientHandoff(Exception):
    """Wrapper so :func:`retry_call` retries exactly the transient handoff
    faults; non-transient errors escape unwrapped into quarantine."""

    def __init__(self, err):
        super().__init__(str(err))
        self.err = err


class _Handoff:
    """One queued prefill→decode transfer: the detached request plus either
    the prefill-side device pages whose refcounts the queue now owns
    (``src`` = local prefill engine index) or, for a cross-host handoff, the
    serialized host page block pulled off a remote prefill tier."""

    __slots__ = ("r", "pages", "n_tokens", "src", "host_block", "path",
                 "t_enqueue", "released")

    def __init__(self, r, pages, n_tokens, src=None, host_block=None,
                 path="local"):
        self.r = r
        self.pages = pages
        self.n_tokens = n_tokens
        self.src = src
        self.host_block = host_block
        self.path = path
        self.t_enqueue = time.perf_counter()
        self.released = False

    @property
    def n_pages(self):
        if self.host_block is None:
            return len(self.pages)
        return int(self.host_block[0].shape[1])


class _Staged:
    """A handoff whose transfer is in flight: destination pages are
    allocated and the gather/device_put dispatched; the landing half
    (scatter + admit) runs after the decode step the copy overlapped."""

    __slots__ = ("h", "j", "dst", "block", "t_staged", "dispatch_s")

    def __init__(self, h, j, dst, block, t_staged, dispatch_s):
        self.h = h
        self.j = j
        self.dst = dst
        self.block = block
        self.t_staged = t_staged
        self.dispatch_s = dispatch_s


class DisaggEngine:
    """M prefill engines + N decode engines + one bounded KV handoff queue.

    Accepts the colocated :class:`LLMEngine` knobs and applies them to both
    sides.  Pool shape: ``n_prefill``/``n_decode`` replicate the engine
    build (``prefill_meshes``/``decode_meshes`` pin each replica to its
    slice — default both 1, two buffer sets on the local device, exactly
    the original 1:1 engine); ``prefill_engines``/``decode_engines`` pass
    pre-built engines instead; ``remote_prefill`` adds remote prefill
    tiers (e.g. :class:`~..frontend.disagg.RemotePrefillTier` handles to
    prefill-role workers) whose handoffs arrive serialized over RPC.
    ``prefix_cache`` lives on the PREFILL side only (that is where prompts
    are computed; a decode-side cache would share the partially-filled
    last prompt page that decode writes into).  ``spec_decode`` lives on
    the DECODE side only.  ``handoff_depth`` bounds the queue;
    ``handoff_retry`` is the :class:`RetryPolicy` for transient
    ``serving.kv_handoff`` faults; ``async_handoff`` pipelines transfers
    under decode compute (False restores the blocking hop)."""

    _pool_seq = 0   # observability label: one series set per pool

    def __init__(self, model=None, prefill_mesh=None, decode_mesh=None,
                 mp_axis="mp", pp_axis="pp", max_batch=4, max_len=256,
                 page_size=16, prefill_chunk=32, page_pool=None,
                 decode_block=1, use_kernel=None, seed=0,
                 kv_cache_dtype="auto", decode_block_max=32,
                 prefix_cache=False, spec_decode=None, max_waiting=None,
                 shed_min_free_ratio=0.0, default_deadline=None,
                 step_retry=None, debug_refcount_audit=False,
                 handoff_depth=4, handoff_retry=None,
                 n_prefill=1, n_decode=1, prefill_meshes=None,
                 decode_meshes=None, prefill_engines=None,
                 decode_engines=None, remote_prefill=None,
                 async_handoff=True):
        self.max_batch = max_batch
        self.max_len = max_len
        self.page = page_size
        self.debug_refcount_audit = bool(debug_refcount_audit)
        self.handoff_depth = int(handoff_depth)
        self._async = bool(async_handoff)
        self._handoff_retry = (handoff_retry if handoff_retry is not None
                               else RetryPolicy(max_attempts=3,
                                                base_delay=0.01,
                                                max_delay=0.25, seed=seed))
        common = dict(mp_axis=mp_axis, pp_axis=pp_axis, max_batch=max_batch,
                      max_len=max_len, page_size=page_size,
                      prefill_chunk=prefill_chunk, page_pool=page_pool,
                      use_kernel=use_kernel, seed=seed,
                      kv_cache_dtype=kv_cache_dtype,
                      default_deadline=default_deadline,
                      step_retry=step_retry)
        # internal engines run with their own audits off — handoff-held
        # pages are invisible to a single engine's slot tables, so only the
        # combined audit_refcounts() below knows the full expected counts
        if prefill_engines is not None:
            self.prefills = list(prefill_engines)
        else:
            meshes = (list(prefill_meshes) if prefill_meshes is not None
                      else [prefill_mesh] * int(n_prefill))
            self.prefills = [
                LLMEngine(model, mesh=m, prefix_cache=prefix_cache,
                          max_waiting=max_waiting,
                          shed_min_free_ratio=shed_min_free_ratio,
                          debug_refcount_audit=False, **common)
                for m in meshes]
        if decode_engines is not None:
            self.decodes = list(decode_engines)
        else:
            meshes = (list(decode_meshes) if decode_meshes is not None
                      else [decode_mesh] * int(n_decode))
            self.decodes = [
                LLMEngine(model, mesh=m, decode_block=decode_block,
                          decode_block_max=decode_block_max,
                          spec_decode=spec_decode,
                          debug_refcount_audit=False, **common)
                for m in meshes]
        self.remote = list(remote_prefill) if remote_prefill else []
        if not self.decodes:
            raise ValueError("DisaggEngine needs at least one decode engine")
        if not self.prefills and not self.remote:
            raise ValueError("DisaggEngine needs at least one prefill "
                             "engine (local or remote)")
        for i, pe in enumerate(self.prefills):
            pe._next_rid += i * _RID_STRIDE
            pe.prefill_sink = (
                lambda slot, token, _i=i: self._sink(_i, slot, token))
        # one hop or zero per (prefill, decode) pair: device_put only when
        # the pair's device sets really differ
        self._cross = [[set(pe.runner.devices) != set(de.runner.devices)
                        for de in self.decodes] for pe in self.prefills]
        self._queue: deque = deque()          # unstaged handoffs, FIFO
        self._queued: dict = {}               # rid -> live _Handoff (O(1))
        self._staged: deque = deque()         # transfers in flight
        self._staged_by_rid: dict = {}
        self._staged_slots = [0] * len(self.decodes)  # slots reserved
        # remote tier bookkeeping: pool_rid -> (tier idx, worker rid,
        # placeholder Request in the POOL's clock domain)
        self._remote_pending: dict = {}
        self._remote_counters = [0] * len(self.remote)
        self._pf_rr = 0                 # round-robin prefill step cursor
        self.handoffs = 0               # completed page transfers
        self.handoff_retries = 0        # transient kv_handoff retries
        self.handoff_failures = 0       # handoffs quarantined as poison
        self.queue_wait_s = 0.0         # total queue wait before dispatch
        self.transfer_s = 0.0           # transfer wall decode could not hide
        self.transfer_overlap_s = 0.0   # in-flight time hidden under decode
        self.prefix_cache = (self.pre.prefix_cache
                             if self.pre is not None else False)
        self._pm = _PoolMetrics(str(DisaggEngine._pool_seq))
        DisaggEngine._pool_seq += 1

    # ------------------------------------------------------------ structure
    @property
    def pre(self):
        """First local prefill engine (the 1:1 back-compat alias; None for
        a pool fed only by remote tiers)."""
        return self.prefills[0] if self.prefills else None

    @property
    def dec(self):
        """First decode engine (the 1:1 back-compat alias)."""
        return self.decodes[0]

    # --------------------------------------------------------------- intake
    def add_request(self, prompt_ids, max_new_tokens, eos_token_id=None,
                    **kw):
        """Submit a request to the least-loaded prefill engine (waiting +
        active; remote tiers weigh in with their locally-tracked inflight
        count, ties prefer local engines in index order).  Admission
        control runs on the chosen prefill side; a full handoff queue
        back-pressures it by pausing prefill steps, which grows the
        waiting queue into the ``max_waiting`` / page-pressure shed
        rules."""
        if len(self.prefills) == 1 and not self.remote:
            return self.pre.add_request(prompt_ids, max_new_tokens,
                                        eos_token_id, **kw)
        cands = [(len(pe.sched.waiting)
                  + sum(1 for s in pe.sched.slots if s is not None), 0, i)
                 for i, pe in enumerate(self.prefills)]
        cands += [(int(getattr(t, "load", lambda: 0)()), 1, j)
                  for j, t in enumerate(self.remote)]
        _, kind, idx = min(cands)
        if kind == 0:
            return self.prefills[idx].add_request(prompt_ids, max_new_tokens,
                                                  eos_token_id, **kw)
        return self._submit_remote(idx, prompt_ids, max_new_tokens,
                                   eos_token_id, **kw)

    def _submit_remote(self, t, prompt_ids, max_new_tokens, eos_token_id,
                       **kw):
        """Route a request to remote prefill tier ``t``: the worker assigns
        its own rid; the pool assigns a pool-wide rid from the tier's
        stride namespace and keeps a placeholder Request so status /
        cancel / deadline expiry work before the block is pulled."""
        tier = self.remote[t]
        wrid = tier.submit(
            [int(x) for x in np.asarray(prompt_ids).reshape(-1)],
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id, **kw)
        pool_rid = ((len(self.prefills) + t) * _RID_STRIDE
                    + self._remote_counters[t])
        self._remote_counters[t] += 1
        placeholder = Request(
            pool_rid, prompt_ids, max_new_tokens, eos_token_id,
            do_sample=kw.get("do_sample", False),
            temperature=kw.get("temperature", 1.0),
            top_p=kw.get("top_p", 1.0), top_k=kw.get("top_k", 0),
            seed=kw.get("seed"), deadline=kw.get("deadline"))
        ctx = _flight.current()
        if ctx is not None:
            placeholder.trace_id = ctx.trace_id
            _flight.record("remote_submit", rid=pool_rid,
                           trace_id=ctx.trace_id, tier=tier.name, wrid=wrid)
        self._remote_pending[pool_rid] = (t, wrid, placeholder)
        return pool_rid

    def cancel(self, rid):
        """Cancel wherever the request lives: a prefill engine, the handoff
        queue (O(1) by rid), a staged in-flight transfer, a remote prefill
        tier, or a decode engine."""
        for pe in self.prefills:
            if pe.cancel(rid):
                return True
        h = self._queued.get(rid)
        if h is not None:
            self._release_queued(h, RequestStatus.CANCELLED)
            return True
        s = self._staged_by_rid.get(rid)
        if s is not None:
            # transfer already in flight: finalize now; _land releases the
            # destination pages when the block arrives
            self.decodes[0].sched.finalize(s.h.r, RequestStatus.CANCELLED)
            return True
        ent = self._remote_pending.pop(rid, None)
        if ent is not None:
            t, wrid, placeholder = ent
            try:
                self.remote[t].cancel(wrid)
            except (ConnectionError, OSError):
                pass          # tier unreachable: membership will reap it
            self.decodes[0].sched.finalize(placeholder,
                                           RequestStatus.CANCELLED)
            return True
        return any(de.cancel(rid) for de in self.decodes)

    # -------------------------------------------------------------- handoff
    def _sink(self, i, slot, token):
        """``prefill_sink`` for local prefill engine ``i``: emit the first
        token there (TTFT is a prefill-side responsibility), then — unless
        that token already finished the request — detach the slot with its
        page refcounts into the shared handoff queue."""
        pe = self.prefills[i]
        r = pe.sched.slots[slot]
        pe.sched.emit(slot, token)
        if pe.sched.slots[slot] is not r:
            return                 # max_new==1 / eos at first token: done
        req, pages, n_tokens = pe.sched.detach(slot)
        if req.trace_id is not None:
            _flight.record("handoff_queued", rid=req.rid,
                           trace_id=req.trace_id, src=i, n_tokens=n_tokens)
        h = _Handoff(req, pages, n_tokens, src=i)
        self._queue.append(h)
        self._queued[req.rid] = h

    def _drop_src_pages(self, h):
        if h.src is not None:
            pool = self.prefills[h.src].pool
            for p in h.pages:
                pool.unref_page(p)
        h.pages = ()

    def _release_queued(self, h, status, error=None):
        """The ONE path that releases a queued handoff's page refs and
        finalizes its request — cancel, deadline expiry, and fail_all all
        land here so the two bookkeeping halves can never drift.  The
        deque keeps a tombstone that ``_stage``/``_drain_sync`` pop lazily
        (cancel stays O(1))."""
        self._queued.pop(h.r.rid, None)
        h.released = True
        self._drop_src_pages(h)
        self.decodes[0].sched.finalize(h.r, status, error=error)

    def _place(self, h):
        """Least-loaded decode placement: among decode engines with a free
        slot (net of slots already reserved by staged transfers) and
        enough free pages, pick the lowest (active + staged + waiting)
        load, ties to the lowest index; allocate and return
        ``(engine_idx, dst_pages)``, or None when nothing can take the
        handoff yet."""
        best, best_load = None, None
        for j, de in enumerate(self.decodes):
            free_slots = (sum(1 for s in de.sched.slots if s is None)
                          - self._staged_slots[j])
            if free_slots <= 0:
                continue
            if de.pool.n_available() < h.n_pages:
                continue
            load = (sum(1 for s in de.sched.slots if s is not None)
                    + self._staged_slots[j] + len(de.sched.waiting))
            if best_load is None or load < best_load:
                best, best_load = j, load
        if best is None:
            return None
        de, dst = self.decodes[best], []
        for _ in range(h.n_pages):
            p = de.pool.alloc_page()
            if p is None:             # raced below n_available: back off
                for q in dst:
                    de.pool.unref_page(q)
                return None
            dst.append(p)
        return best, dst

    def _dispatch(self, h, j):
        """Fire the fault point, then dispatch the transfer: jitted gather
        off the source slice plus ``device_put`` onto the decode slice's
        sharding when the pair crosses device sets (a cross-host block is
        already host-resident and only needs the put).  Dispatch is
        asynchronous — the returned device block is in flight, and the
        landing scatter chains on it.  Transient faults retry under the
        shared policy; the fault fires before any copy, so a retry is
        idempotent."""
        def attempt():
            _faults.maybe_fire("serving.kv_handoff", rids=[h.r.rid],
                               path=h.path)
            if h.host_block is not None:
                return self.decodes[j].runner.put_block(h.host_block)
            block = self.prefills[h.src].runner.gather_pages(h.pages)
            if self._cross[h.src][j]:
                block = self.decodes[j].runner.put_block(block)
            return block

        def xfer():
            try:
                return attempt()
            except Exception as err:
                if getattr(err, "transient", False):
                    self.handoff_retries += 1
                    raise _TransientHandoff(err) from err
                raise

        return retry_call(xfer, policy=self._handoff_retry,
                          retry_on=(_TransientHandoff,),
                          op="serving.kv_handoff")

    def _next_placeable(self):
        """Head of the handoff queue placed onto a decode engine, with
        tombstones from O(1) cancel/expiry popped along the way.  FIFO —
        order preserves fairness; a head that cannot be placed blocks the
        queue.  Returns ``(handoff, engine_idx, dst_pages)`` or None."""
        while self._queue:
            h = self._queue[0]
            if h.released or h.r.status.terminal:
                self._queue.popleft()
                continue
            placed = self._place(h)
            if placed is None:
                return None
            self._queue.popleft()
            self._queued.pop(h.r.rid, None)
            wait = time.perf_counter() - h.t_enqueue
            self.queue_wait_s += wait
            self._pm.wait[h.path].observe(wait)
            return h, placed[0], placed[1]
        return None

    def _quarantine(self, h, j, dst, err):
        if isinstance(err, RetryError):
            err = err.__cause__.err
        self.handoff_failures += 1
        de = self.decodes[j]
        for p in dst:
            de.pool.unref_page(p)
        self._drop_src_pages(h)
        de.sched.finalize(h.r, RequestStatus.FAILED, error=err)
        if h.r.trace_id is not None:
            # pin AFTER finalize so the dumped post-mortem includes the
            # terminal span
            _flight.pin(h.r.trace_id, "poison_quarantine")

    def _stage(self):
        """Async pipeline, send half: dispatch the transfer for every
        placeable queued handoff and reserve its decode slot.  The copies
        run while the NEXT decode step computes; ``_land`` completes
        them."""
        while True:
            nxt = self._next_placeable()
            if nxt is None:
                return
            h, j, dst = nxt
            t0 = time.perf_counter()
            try:
                block = self._dispatch(h, j)
            except Exception as err:  # noqa: BLE001 — quarantine boundary
                self._quarantine(h, j, dst, err)
                continue
            dispatch_s = time.perf_counter() - t0
            if h.r.trace_id is not None:
                _flight.record("handoff_dispatch", rid=h.r.rid,
                               trace_id=h.r.trace_id, dur=dispatch_s,
                               dst=j, path=h.path)
            # the dispatched gather owns the data: source refs can go now,
            # parking content-registered prompt pages in the prefill LRU
            self._drop_src_pages(h)
            s = _Staged(h, j, dst, block, time.perf_counter(), dispatch_s)
            self._staged.append(s)
            self._staged_by_rid[h.r.rid] = s
            self._staged_slots[j] += 1

    def _land(self):
        """Async pipeline, receive half: seat every staged transfer whose
        copy the decode step just overlapped — admit into the reserved
        slot, then scatter the block into the destination pages.  A
        request cancelled while in flight only releases its destination
        pages here."""
        while self._staged:
            s = self._staged[0]
            de = self.decodes[s.j]
            if s.h.r.status.terminal:       # cancelled/failed in flight
                self._staged.popleft()
                self._staged_by_rid.pop(s.h.r.rid, None)
                self._staged_slots[s.j] -= 1
                for p in s.dst:
                    de.pool.unref_page(p)
                continue
            t0 = time.perf_counter()
            slot = de.sched.admit_prefilled(s.h.r, s.dst, s.h.n_tokens)
            if slot is None:
                # a preemption readmit took the reserved slot: wait for
                # the next step's _land, pages and block stay held
                return
            self._staged.popleft()
            self._staged_by_rid.pop(s.h.r.rid, None)
            self._staged_slots[s.j] -= 1
            de.runner.scatter_pages(s.dst, s.block)
            land_s = time.perf_counter() - t0
            if s.h.r.trace_id is not None:
                _flight.record("handoff_land", rid=s.h.r.rid,
                               trace_id=s.h.r.trace_id, dur=land_s,
                               dst=s.j, path=s.h.path)
            self.transfer_s += s.dispatch_s + land_s
            self.transfer_overlap_s += max(0.0, t0 - s.t_staged)
            self._pm.transfer[s.h.path].observe(s.dispatch_s + land_s)
            self.handoffs += 1

    def _drain_sync(self):
        """Blocking hop (``async_handoff=False``): move every placeable
        handoff into a decode slot inline — gather, device_put, scatter,
        admit, all before the next decode step dispatches.  The original
        1:1 engine's behavior, kept as the bench's sync comparator."""
        while True:
            nxt = self._next_placeable()
            if nxt is None:
                return
            h, j, dst = nxt
            de = self.decodes[j]
            t0 = time.perf_counter()
            try:
                block = self._dispatch(h, j)
            except Exception as err:  # noqa: BLE001 — quarantine boundary
                self._quarantine(h, j, dst, err)
                continue
            de.runner.scatter_pages(dst, block)
            de.sched.admit_prefilled(h.r, dst, h.n_tokens)
            self._drop_src_pages(h)
            dt = time.perf_counter() - t0
            if h.r.trace_id is not None:
                _flight.record("handoff_land", rid=h.r.rid,
                               trace_id=h.r.trace_id, dur=dt, dst=j,
                               path=h.path)
            self.transfer_s += dt
            self._pm.transfer[h.path].observe(dt)
            self.handoffs += 1

    def _expire_queue(self):
        """Deadline expiry for work the pool itself holds: queued handoffs
        release through the same shared path as cancel; remote pending
        placeholders cancel tier-side and finalize TIMEOUT locally."""
        now = time.perf_counter()
        expired = [h for h in self._queued.values()
                   if h.r.deadline is not None and now > h.r.deadline]
        for h in expired:
            self._release_queued(h, RequestStatus.TIMEOUT)
        for pool_rid, (t, wrid, placeholder) in list(
                self._remote_pending.items()):
            if placeholder.deadline is None or now <= placeholder.deadline:
                continue
            del self._remote_pending[pool_rid]
            try:
                self.remote[t].cancel(wrid)
            except (ConnectionError, OSError):
                pass
            self.decodes[0].sched.finalize(placeholder,
                                           RequestStatus.TIMEOUT)

    # --------------------------------------------------------- remote tiers
    def _fail_tier(self, t, err):
        """A remote tier's channel died: fail its pending requests with a
        typed terminal status instead of hanging them forever."""
        for pool_rid, ent in list(self._remote_pending.items()):
            if ent[0] != t:
                continue
            del self._remote_pending[pool_rid]
            self.decodes[0].sched.finalize(ent[2], RequestStatus.FAILED,
                                           error=err)

    def _pull_remote(self):
        """Pull finished prefills off every remote tier into the shared
        handoff queue (bounded by ``handoff_depth`` — backpressure crosses
        the host boundary too).  The ``serving.kv_handoff`` fault fires
        pool-side BEFORE the pull RPC (ctx ``path="cross_host"``), so a
        transient retry re-issues the pull against a worker that still
        holds the block; poison quarantines only that request on both
        sides."""
        for t, tier in enumerate(self.remote):
            if not any(ent[0] == t for ent in self._remote_pending.values()):
                continue
            if len(self._queued) >= self.handoff_depth:
                return
            try:
                ready = tier.poll_ready()
            except (ConnectionError, OSError) as err:
                self._fail_tier(t, err)
                continue
            by_worker = {ent[1]: pool_rid for pool_rid, ent
                         in self._remote_pending.items() if ent[0] == t}
            for wrid in ready:
                pool_rid = by_worker.get(wrid)
                if pool_rid is None:
                    continue          # not ours / already resolved
                if len(self._queued) >= self.handoff_depth:
                    break
                self._pull_one(t, tier, wrid, pool_rid)

    def _pull_one(self, t, tier, wrid, pool_rid):
        def pull():
            try:
                _faults.maybe_fire("serving.kv_handoff", rids=[pool_rid],
                                   path="cross_host")
                return tier.pull(wrid)
            except Exception as err:
                if getattr(err, "transient", False):
                    self.handoff_retries += 1
                    raise _TransientHandoff(err) from err
                raise

        try:
            payload = retry_call(pull, policy=self._handoff_retry,
                                 retry_on=(_TransientHandoff,),
                                 op="serving.kv_handoff")
        except Exception as err:  # noqa: BLE001 — quarantine boundary
            if isinstance(err, RetryError):
                err = err.__cause__.err
            self.handoff_failures += 1
            _, _, placeholder = self._remote_pending.pop(pool_rid)
            try:
                tier.fail(wrid)
            except (ConnectionError, OSError):
                pass
            self.decodes[0].sched.finalize(placeholder, RequestStatus.FAILED,
                                           error=err)
            if placeholder.trace_id is not None:
                _flight.pin(placeholder.trace_id, "poison_quarantine")
            return
        _, _, placeholder = self._remote_pending.pop(pool_rid)
        r = payload["req"]
        # rebase into the pool's namespace and clock domain: the worker's
        # perf_counter origin is not ours, and its rid is not unique here
        r.rid = pool_rid
        r.t_submit = placeholder.t_submit
        r.deadline = placeholder.deadline
        r.stream_pos = 0
        if r.trace_id is None:
            r.trace_id = placeholder.trace_id
        if r.trace_id is not None:
            _flight.record("handoff_pulled", rid=pool_rid,
                           trace_id=r.trace_id, tier=tier.name, wrid=wrid)
        if payload["block"] is None:
            # finished at the first prefill token (max_new==1 / instant
            # eos): terminal worker-side, nothing to transfer — record the
            # completed request pool-side as-is
            self.decodes[0].sched.finished[pool_rid] = r
            return
        h = _Handoff(r, (), int(payload["n_tokens"]), src=None,
                     host_block=payload["block"], path="cross_host")
        self._queue.append(h)
        self._queued[pool_rid] = h

    # ----------------------------------------------------------------- step
    def step(self):
        """One disaggregated scheduling round.  Async (default): land the
        transfers staged LAST round (scatter + admit — their copies had a
        full round to fly), stage freshly queued ones (dispatch gather +
        device_put), then step every decode engine; transfer k overlaps
        round k's tail and the requests it carries decode in round k+1,
        same seating latency as the blocking hop but without its stall.
        Sync: drain inline before the decode step (the blocking hop).
        Prefill engines step only while the handoff queue has room
        (backpressure), and fresh handoffs stage immediately so their copy
        overlaps the NEXT decode step.  Returns #slots served across all
        slices."""
        if self._queued or self._remote_pending:
            self._expire_queue()
        if self._remote_pending:
            self._pull_remote()
        if self._async:
            self._land()
            self._stage()
        else:
            self._drain_sync()
        served = 0
        for de in self.decodes:
            served += de.step()
        # at most ONE prefill engine steps per pool round (round-robin over
        # the busy ones): the in-process pool serializes all dispatch, so
        # stepping every busy engine would grow the per-round wall O(M) and
        # re-block the decode cadence disaggregation exists to protect.
        # Remote tiers prefill truly in parallel in their own processes.
        n_pf = len(self.prefills)
        for k in range(n_pf):
            if len(self._queued) >= self.handoff_depth:
                break
            i = (self._pf_rr + k) % n_pf
            pe = self.prefills[i]
            if (pe.sched.waiting
                    or any(s is not None for s in pe.sched.slots)):
                served += pe.step()
                self._pf_rr = (i + 1) % n_pf
                break
        # a prompt that just finished prefilling goes straight for a decode
        # slot: sync admits now, async dispatches the copy so it hides
        # under the next step's decode
        if self._queue:
            if self._async:
                self._stage()
            else:
                self._drain_sync()
        self._pm.queue_depth.set(len(self._queued))
        if self.debug_refcount_audit:
            problems = self.audit_refcounts()
            if problems:
                raise RuntimeError("page-refcount audit failed:\n  "
                                   + "\n  ".join(problems))
        return served

    def run_until_done(self, max_steps=10000):
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return steps

    def has_work(self):
        return bool(
            self._queued or self._staged or self._remote_pending
            or any(pe.sched.waiting
                   or any(s is not None for s in pe.sched.slots)
                   for pe in self.prefills)
            or any(de.sched.waiting
                   or any(s is not None for s in de.sched.slots)
                   for de in self.decodes))

    # ------------------------------------------------------------ accessors
    def _lookup(self, rid):
        for pe in self.prefills:
            for r in pe.sched.waiting:
                if r.rid == rid:
                    return r
            for r in pe.sched.slots:
                if r is not None and r.rid == rid:
                    return r
        h = self._queued.get(rid)
        if h is not None:
            return h.r
        s = self._staged_by_rid.get(rid)
        if s is not None:
            return s.h.r
        ent = self._remote_pending.get(rid)
        if ent is not None:
            return ent[2]
        for de in self.decodes:
            for r in de.sched.slots:
                if r is not None and r.rid == rid:
                    return r
            for r in de.sched.waiting:    # decode-side preemption requeue
                if r.rid == rid:
                    return r
            if rid in de.sched.finished:
                return de.sched.finished[rid]
        for pe in self.prefills:
            if rid in pe.sched.finished:
                return pe.sched.finished[rid]
        raise KeyError(rid)

    def result(self, rid):
        r = self._lookup(rid)
        if not r.status.terminal:
            raise KeyError(rid)
        return r.out

    def status(self, rid):
        return self._lookup(rid).status

    def error(self, rid):
        return self._lookup(rid).error

    def ttft(self, rid):
        return self._lookup(rid).ttft

    def tpot(self, rid):
        r = self._lookup(rid)
        if r.t_finish is None or r.ttft is None or len(r.out) < 2:
            return None
        return (r.t_finish - r.t_submit - r.ttft) / (len(r.out) - 1)

    def new_tokens(self, rid):
        r = self._lookup(rid)
        toks = [int(t) for t in r.out[r.stream_pos:]]
        r.stream_pos += len(toks)
        return toks

    def fail_all(self, error):
        for pe in self.prefills:
            pe.fail_all(error)
        for h in list(self._queued.values()):
            self._release_queued(h, RequestStatus.FAILED, error=error)
        self._queue.clear()
        while self._staged:
            s = self._staged.popleft()
            self._staged_by_rid.pop(s.h.r.rid, None)
            self._staged_slots[s.j] -= 1
            de = self.decodes[s.j]
            for p in s.dst:
                de.pool.unref_page(p)
            if not s.h.r.status.terminal:
                de.sched.finalize(s.h.r, RequestStatus.FAILED, error=error)
        for pool_rid, (t, wrid, placeholder) in list(
                self._remote_pending.items()):
            del self._remote_pending[pool_rid]
            try:
                self.remote[t].cancel(wrid)
            except (ConnectionError, OSError):
                pass
            self.decodes[0].sched.finalize(placeholder, RequestStatus.FAILED,
                                           error=error)
        for de in self.decodes:
            de.fail_all(error)

    def audit_refcounts(self):
        """Combined page-accounting audit across EVERY slice: each prefill
        pool's expected refcounts include the handoff queue's holds (pages
        detached from a slot but not yet dispatched), each decode pool's
        include the staged transfers' destination pages (allocated but not
        yet seated in a slot table); remote tiers are asked to audit
        themselves over RPC.  Empty list means clean."""
        problems = []
        for i, pe in enumerate(self.prefills):
            expected = pe.sched.expected_refs(pe.n_pages)
            for h in self._queued.values():
                if h.src == i:
                    for p in h.pages:
                        expected[p] += 1
            tag = "prefill" if len(self.prefills) == 1 else f"prefill[{i}]"
            problems += [f"{tag}: {m}" for m in pe.pool.audit(expected)]
        for j, de in enumerate(self.decodes):
            expected = de.sched.expected_refs(de.n_pages)
            for s in self._staged:
                if s.j == j:
                    for p in s.dst:
                        expected[p] += 1
            tag = "decode" if len(self.decodes) == 1 else f"decode[{j}]"
            problems += [f"{tag}: {m}" for m in de.pool.audit(expected)]
        for t, tier in enumerate(self.remote):
            fn = getattr(tier, "audit", None)
            if fn is None:
                continue
            try:
                problems += [f"remote[{t}]: {m}" for m in fn()]
            except (ConnectionError, OSError) as err:
                problems += [f"remote[{t}]: audit unreachable: {err}"]
        return problems

    def spec_stats(self):
        if len(self.decodes) == 1:
            return self.dec.spec_stats()
        agg: dict = {}
        for de in self.decodes:
            for k, v in de.spec_stats().items():
                agg[k] = (agg.get(k, 0) + v
                          if isinstance(v, (int, float)) else v)
        return agg

    def prefix_cache_stats(self):
        if self.pre is None:
            return {}
        if len(self.prefills) == 1:
            return self.pre.prefix_cache_stats()
        agg: dict = {}
        for pe in self.prefills:
            for k, v in pe.prefix_cache_stats().items():
                agg[k] = (agg.get(k, 0) + v
                          if isinstance(v, (int, float)) else v)
        return agg

    def handoff_stats(self):
        """Always-on counters and timings for the prefill→decode seam —
        the in-process mirror of the ``serving_handoff_*`` registry
        families.  ``queue_wait_s`` totals time handoffs sat queued before
        their transfer dispatched; ``transfer_s`` totals transfer wall the
        decode loop could NOT hide (async: dispatch + land halves; sync:
        the whole blocking hop); ``transfer_overlap_s`` totals in-flight
        time hidden under decode compute (async only — the pipelining
        evidence)."""
        return {
            "handoffs": self.handoffs,
            "queued": len(self._queued),
            "staged": len(self._staged),
            "remote_pending": len(self._remote_pending),
            "depth": self.handoff_depth,
            "retries": self.handoff_retries,
            "failures": self.handoff_failures,
            "cross_device": (any(any(row) for row in self._cross)
                             or bool(self.remote)),
            "async": self._async,
            "n_prefill": len(self.prefills) + len(self.remote),
            "n_decode": len(self.decodes),
            "queue_wait_s": self.queue_wait_s,
            "transfer_s": self.transfer_s,
            "transfer_overlap_s": self.transfer_overlap_s,
        }

    def health(self):
        """Combined liveness snapshot: per-slice engine health plus the
        handoff seam counters (1:1 keeps the original ``prefill`` /
        ``decode`` keys; larger pools add per-replica lists)."""
        h = {
            "prefill": self.pre.health() if self.pre is not None else None,
            "decode": self.dec.health(),
            "handoff": self.handoff_stats(),
        }
        if len(self.prefills) > 1:
            h["prefills"] = [pe.health() for pe in self.prefills]
        if len(self.decodes) > 1:
            h["decodes"] = [de.health() for de in self.decodes]
        return h

    @property
    def preemptions(self):
        return (sum(pe.sched.preemptions for pe in self.prefills)
                + sum(de.sched.preemptions for de in self.decodes))
