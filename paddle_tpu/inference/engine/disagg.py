"""Disaggregated prefill/decode serving: two engine cores on disjoint mesh
slices with KV-page handoff between them.

The phase-separation argument (DistServe OSDI'24, Splitwise ISCA'24): in a
colocated engine every chunked prefill that lands in a step stalls ALL
co-resident decode slots — the step loop is prefill-first, so a long prompt
arriving mid-stream inflates every other request's inter-token latency.
:class:`DisaggEngine` runs a PREFILL engine on one mesh slice and a DECODE
engine on another; each :meth:`step` always dispatches the decode side and
only additionally dispatches a prefill chunk when the handoff queue has
room, so decode token cadence is never blocked behind a prompt — even on a
single device, where "slices" are just two independent buffer sets.

The seam is the KV-page handoff: when a prompt finishes prefilling, the
prefill engine's ``prefill_sink`` detaches the request WITH its page
refcounts into a bounded queue; the drain loop allocates destination pages
on the decode pool, moves the page contents device-to-device (a jitted
gather → ``jax.device_put`` onto the decode slice's sharding → jitted
scatter; the device_put collapses to a no-op when both engines share one
device set), seats the request via ``admit_prefilled``, and releases the
source pages (content-registered prompt pages park in the prefill LRU, so
prefix-cache hits survive disaggregation).  A full queue back-pressures
admission: the prefill engine stops stepping, its waiting queue grows, and
the ordinary ``max_waiting`` / page-pressure shedding applies.

Fault surface: each handoff fires the ``serving.kv_handoff`` point —
transient faults retry under the shared :class:`RetryPolicy`; a poisoned
handoff quarantines ONLY that request (terminal FAILED, pages released on
both slices).

Parity: greedy and fixed-seed requests are token-exact with a colocated
:class:`~.core.LLMEngine` — the copied pages are bit-identical to what the
decode slice would have written (same program, same absolute RoPE
positions; int8 pages and scales copy verbatim), and per-request sampling
seeds do not depend on dispatch structure.  (Seedless sampling draws from a
per-engine global counter and is not parity-stable, exactly as with the
colocated prefix cache.)
"""
from __future__ import annotations

import numpy as np
import jax

from ... import observability as _obs
from ...core.retry import RetryError, RetryPolicy, retry_call
from ...testing.faults import FAULTS as _faults
from .core import LLMEngine
from .request import RequestStatus

__all__ = ["DisaggEngine", "split_mesh"]


def split_mesh(mesh, axis=None):
    """Split ``mesh`` into ``(prefill_mesh, decode_mesh)`` halves along
    ``axis`` (default: the first axis with even size >= 2).  Both halves
    keep every axis name, so the engines' pp×mp shardings apply unchanged
    to their slice."""
    from jax.sharding import Mesh
    names = mesh.axis_names
    if axis is None:
        axis = next((n for n in names
                     if mesh.shape[n] >= 2 and mesh.shape[n] % 2 == 0), None)
        if axis is None:
            raise ValueError(
                f"no mesh axis with even size >= 2 to split (shape "
                f"{dict(mesh.shape)}); pass prefill_mesh/decode_mesh "
                "explicitly")
    ai = list(names).index(axis)
    devs = mesh.devices
    half = devs.shape[ai] // 2
    sl = [slice(None)] * devs.ndim
    sl[ai] = slice(0, half)
    pre = devs[tuple(sl)]
    sl[ai] = slice(half, None)
    dec = devs[tuple(sl)]
    return Mesh(pre, names), Mesh(dec, names)


class _TransientHandoff(Exception):
    """Wrapper so :func:`retry_call` retries exactly the transient handoff
    faults; non-transient errors escape unwrapped into quarantine."""

    def __init__(self, err):
        super().__init__(str(err))
        self.err = err


class _Handoff:
    """One queued prefill→decode transfer: the detached request plus the
    prefill-side pages whose refcounts the queue now owns."""

    __slots__ = ("r", "pages", "n_tokens")

    def __init__(self, r, pages, n_tokens):
        self.r = r
        self.pages = pages
        self.n_tokens = n_tokens


class DisaggEngine:
    """Prefill engine + decode engine + bounded KV-page handoff queue.

    Accepts the colocated :class:`LLMEngine` knobs and applies them to both
    sides; ``prefill_mesh`` / ``decode_mesh`` pin each phase to its slice
    (both None = two buffer sets on the local device — functionally
    disaggregated, used by the parity tests).  ``prefix_cache`` lives on the
    PREFILL side only (that is where prompts are computed; a decode-side
    cache would share the partially-filled last prompt page that decode
    writes into).  ``spec_decode`` lives on the DECODE side only.
    ``handoff_depth`` bounds the queue; ``handoff_retry`` is the
    :class:`RetryPolicy` for transient ``serving.kv_handoff`` faults."""

    def __init__(self, model, prefill_mesh=None, decode_mesh=None,
                 mp_axis="mp", pp_axis="pp", max_batch=4, max_len=256,
                 page_size=16, prefill_chunk=32, page_pool=None,
                 decode_block=1, use_kernel=None, seed=0,
                 kv_cache_dtype="auto", decode_block_max=32,
                 prefix_cache=False, spec_decode=None, max_waiting=None,
                 shed_min_free_ratio=0.0, default_deadline=None,
                 step_retry=None, debug_refcount_audit=False,
                 handoff_depth=4, handoff_retry=None):
        self.max_batch = max_batch
        self.max_len = max_len
        self.page = page_size
        self.debug_refcount_audit = bool(debug_refcount_audit)
        self.handoff_depth = int(handoff_depth)
        self._handoff_retry = (handoff_retry if handoff_retry is not None
                               else RetryPolicy(max_attempts=3,
                                                base_delay=0.01,
                                                max_delay=0.25, seed=seed))
        common = dict(mp_axis=mp_axis, pp_axis=pp_axis, max_batch=max_batch,
                      max_len=max_len, page_size=page_size,
                      prefill_chunk=prefill_chunk, page_pool=page_pool,
                      use_kernel=use_kernel, seed=seed,
                      kv_cache_dtype=kv_cache_dtype,
                      default_deadline=default_deadline,
                      step_retry=step_retry)
        # internal engines run with their own audits off — handoff-held
        # pages are invisible to a single engine's slot tables, so only the
        # combined audit_refcounts() below knows the full expected counts
        self.pre = LLMEngine(model, mesh=prefill_mesh,
                             prefix_cache=prefix_cache,
                             max_waiting=max_waiting,
                             shed_min_free_ratio=shed_min_free_ratio,
                             debug_refcount_audit=False, **common)
        self.dec = LLMEngine(model, mesh=decode_mesh,
                             decode_block=decode_block,
                             decode_block_max=decode_block_max,
                             spec_decode=spec_decode,
                             debug_refcount_audit=False, **common)
        self.pre.prefill_sink = self._sink
        # one hop or zero: device_put only when the slices really differ
        self._cross_device = (set(self.pre.runner.devices)
                              != set(self.dec.runner.devices))
        from collections import deque
        self._queue: deque = deque()
        self.handoffs = 0               # completed page transfers
        self.handoff_retries = 0        # transient kv_handoff retries
        self.handoff_failures = 0       # handoffs quarantined as poison
        self.prefix_cache = self.pre.prefix_cache

    # --------------------------------------------------------------- intake
    def add_request(self, *args, **kwargs):
        """Submit a request (colocated signature).  Admission control runs
        on the prefill side; a full handoff queue back-pressures it by
        pausing prefill steps, which grows the waiting queue into the
        ``max_waiting`` / page-pressure shed rules."""
        return self.pre.add_request(*args, **kwargs)

    def cancel(self, rid):
        """Cancel wherever the request lives: prefill side, handoff queue,
        or decode side."""
        if self.pre.cancel(rid):
            return True
        for i, h in enumerate(self._queue):
            if h.r.rid == rid:
                del self._queue[i]
                self._drop_prefill_pages(h.pages)
                self.dec.sched.finalize(h.r, RequestStatus.CANCELLED)
                return True
        return self.dec.cancel(rid)

    # -------------------------------------------------------------- handoff
    def _sink(self, slot, token):
        """``prefill_sink`` for the prefill engine: emit the first token
        there (TTFT is a prefill-side responsibility), then — unless that
        token already finished the request — detach the slot with its page
        refcounts into the handoff queue."""
        pre = self.pre
        r = pre.sched.slots[slot]
        pre.sched.emit(slot, token)
        if pre.sched.slots[slot] is not r:
            return                 # max_new==1 / eos at first token: done
        entry = _Handoff(*pre.sched.detach(slot))
        self._queue.append(entry)

    def _drop_prefill_pages(self, pages):
        for p in pages:
            self.pre.pool.unref_page(p)

    def _transfer(self, r, src_pages, dst_pages):
        """Move page contents prefill slice → decode slice.  Jitted gather
        and scatter per block size; the device_put between them is the only
        cross-slice hop and disappears when both engines share a device
        set."""
        if _faults.active:
            _faults.raise_if("serving.kv_handoff", rids=[r.rid])
        with _obs.trace_span("serving.kv_handoff"):
            block = self.pre.runner.gather_pages(src_pages)
            if self._cross_device:
                sh = self.dec.runner.cache_sharding
                if sh is not None:
                    block = tuple(jax.device_put(a, sh) for a in block)
                else:
                    dev = self.dec.runner.devices[0]
                    block = tuple(jax.device_put(a, dev) for a in block)
            self.dec.runner.scatter_pages(dst_pages, block)

    def _drain(self):
        """Move every ready handoff into a decode slot.  An entry waits (the
        queue is FIFO — order preserves fairness) until the decode side has
        a free slot AND enough free pages; transient transfer faults retry,
        poison quarantines only that request with pages released on both
        slices."""
        dec = self.dec
        while self._queue:
            h = self._queue[0]
            if h.r.status.terminal:       # cancelled/expired while queued
                self._queue.popleft()
                self._drop_prefill_pages(h.pages)
                continue
            slot = dec.sched.free_slot()
            if slot is None:
                break
            if dec.pool.n_available() < len(h.pages):
                break
            self._queue.popleft()
            dst = []
            for _ in h.pages:
                p = dec.pool.alloc_page()
                if p is None:             # raced below n_available: requeue
                    break
                dst.append(p)
            if len(dst) < len(h.pages):
                for p in dst:
                    dec.pool.unref_page(p)
                self._queue.appendleft(h)
                break

            def xfer():
                try:
                    self._transfer(h.r, h.pages, dst)
                except Exception as err:
                    if getattr(err, "transient", False):
                        self.handoff_retries += 1
                        raise _TransientHandoff(err) from err
                    raise

            try:
                retry_call(xfer, policy=self._handoff_retry,
                           retry_on=(_TransientHandoff,),
                           op="serving.kv_handoff")
            except Exception as err:  # noqa: BLE001 — quarantine boundary
                if isinstance(err, RetryError):
                    err = err.__cause__.err
                self.handoff_failures += 1
                for p in dst:
                    dec.pool.unref_page(p)
                self._drop_prefill_pages(h.pages)
                dec.sched.finalize(h.r, RequestStatus.FAILED, error=err)
                continue
            dec.sched.admit_prefilled(h.r, dst, h.n_tokens)
            self._drop_prefill_pages(h.pages)
            self.handoffs += 1

    def _expire_queue(self):
        import time
        now = time.perf_counter()
        expired = [h for h in self._queue
                   if h.r.deadline is not None and now > h.r.deadline]
        for h in expired:
            self._queue.remove(h)
            self._drop_prefill_pages(h.pages)
            self.dec.sched.finalize(h.r, RequestStatus.TIMEOUT)

    # ----------------------------------------------------------------- step
    def step(self):
        """One disaggregated scheduling round: drain ready handoffs, ALWAYS
        step the decode engine (its token cadence never waits on a prompt),
        and step the prefill engine only while the handoff queue has room
        (backpressure).  Returns #slots served across both slices."""
        if self._queue:
            self._expire_queue()
            self._drain()
        served = self.dec.step()
        if len(self._queue) < self.handoff_depth and (
                self.pre.sched.waiting
                or any(s is not None for s in self.pre.sched.slots)):
            served += self.pre.step()
            # a prompt that just finished prefilling goes straight for a
            # decode slot — next step's decode can already carry it
            if self._queue:
                self._drain()
        if self.debug_refcount_audit:
            problems = self.audit_refcounts()
            if problems:
                raise RuntimeError("page-refcount audit failed:\n  "
                                   + "\n  ".join(problems))
        return served

    def run_until_done(self, max_steps=10000):
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return steps

    def has_work(self):
        return bool(self.pre.sched.waiting or self._queue
                    or any(s is not None for s in self.pre.sched.slots)
                    or self.dec.sched.waiting
                    or any(s is not None for s in self.dec.sched.slots))

    # ------------------------------------------------------------ accessors
    def _lookup(self, rid):
        for r in self.pre.sched.waiting:
            if r.rid == rid:
                return r
        for r in self.pre.sched.slots:
            if r is not None and r.rid == rid:
                return r
        for h in self._queue:
            if h.r.rid == rid:
                return h.r
        for r in self.dec.sched.slots:
            if r is not None and r.rid == rid:
                return r
        for r in self.dec.sched.waiting:    # decode-side preemption requeue
            if r.rid == rid:
                return r
        if rid in self.dec.sched.finished:
            return self.dec.sched.finished[rid]
        return self.pre.sched.finished[rid]

    def result(self, rid):
        r = self._lookup(rid)
        if not r.status.terminal:
            raise KeyError(rid)
        return r.out

    def status(self, rid):
        return self._lookup(rid).status

    def error(self, rid):
        return self._lookup(rid).error

    def ttft(self, rid):
        return self._lookup(rid).ttft

    def tpot(self, rid):
        r = self._lookup(rid)
        if r.t_finish is None or r.ttft is None or len(r.out) < 2:
            return None
        return (r.t_finish - r.t_submit - r.ttft) / (len(r.out) - 1)

    def new_tokens(self, rid):
        r = self._lookup(rid)
        toks = [int(t) for t in r.out[r.stream_pos:]]
        r.stream_pos += len(toks)
        return toks

    def fail_all(self, error):
        self.pre.fail_all(error)
        while self._queue:
            h = self._queue.popleft()
            self._drop_prefill_pages(h.pages)
            self.dec.sched.finalize(h.r, RequestStatus.FAILED, error=error)
        self.dec.fail_all(error)

    def audit_refcounts(self):
        """Combined page-accounting audit across BOTH slices: the prefill
        pool's expected refcounts include the handoff queue's holds (pages
        detached from a slot but not yet transferred), the decode pool's
        are its slot tables alone.  Empty list means clean."""
        pre_expected = self.pre.sched.expected_refs(self.pre.n_pages)
        for h in self._queue:
            for p in h.pages:
                pre_expected[p] += 1
        problems = [f"prefill: {msg}"
                    for msg in self.pre.pool.audit(pre_expected)]
        dec_expected = self.dec.sched.expected_refs(self.dec.n_pages)
        problems += [f"decode: {msg}"
                     for msg in self.dec.pool.audit(dec_expected)]
        return problems

    def spec_stats(self):
        return self.dec.spec_stats()

    def prefix_cache_stats(self):
        return self.pre.prefix_cache_stats()

    def handoff_stats(self):
        """Always-on counters for the prefill→decode seam."""
        return {
            "handoffs": self.handoffs,
            "queued": len(self._queue),
            "depth": self.handoff_depth,
            "retries": self.handoff_retries,
            "failures": self.handoff_failures,
            "cross_device": self._cross_device,
        }

    def health(self):
        """Combined liveness snapshot: per-slice engine health plus the
        handoff seam counters."""
        return {
            "prefill": self.pre.health(),
            "decode": self.dec.health(),
            "handoff": self.handoff_stats(),
        }

    @property
    def preemptions(self):
        return self.pre.sched.preemptions + self.dec.sched.preemptions
