"""Legacy delegation surface for the pre-split engine API.

The pre-split engine kept every structure as a private attribute; tests
and the frontend reach for them (read / in-place mutation), so the facade
forwards each name to the component that owns it now.  This mixin is pure
delegation — every property touches only ``self.sched`` / ``self.pool`` /
``self.runner``, which :class:`~.core.LLMEngine.__init__` constructs —
and exists so the facade module stays the orchestration logic alone.
"""
from __future__ import annotations

__all__ = ["_LegacyDelegation"]


class _LegacyDelegation:
    """Read (and where the old API allowed it, write) forwarding of the
    pre-split ``LLMEngine`` attribute surface onto the split components."""

    @property
    def _slots(self):
        return self.sched.slots

    @property
    def _waiting(self):
        return self.sched.waiting

    @property
    def _finished(self):
        return self.sched.finished

    @property
    def _lens(self):
        return self.sched.lens

    @property
    def _n_alloc(self):
        return self.sched.n_alloc

    @property
    def _slot_tables(self):
        return self.sched.slot_tables

    @property
    def _free_pages(self):
        return self.pool.free_pages

    @property
    def _lru(self):
        return self.pool.lru

    @property
    def _page_ref(self):
        return self.pool.page_ref

    @property
    def _page_key(self):
        return self.pool.page_key

    @property
    def _key_page(self):
        return self.pool.key_page

    @property
    def cache_hits(self):
        return self.pool.cache_hits

    @property
    def cache_misses(self):
        return self.pool.cache_misses

    @property
    def cache_evictions(self):
        return self.pool.cache_evictions

    @property
    def cache_cow_copies(self):
        return self.pool.cache_cow_copies

    @property
    def preemptions(self):
        return self.sched.preemptions

    @property
    def shed_requests(self):
        return self.sched.shed_requests

    @property
    def timeouts(self):
        return self.sched.timeouts

    @property
    def cancels(self):
        return self.sched.cancels

    @property
    def quarantined(self):
        return self.sched.quarantined

    @property
    def max_waiting(self):
        return self.sched.max_waiting

    @max_waiting.setter
    def max_waiting(self, v):
        self.sched.max_waiting = v

    @property
    def shed_min_free_ratio(self):
        return self.sched.shed_min_free_ratio

    @shed_min_free_ratio.setter
    def shed_min_free_ratio(self, v):
        self.sched.shed_min_free_ratio = v

    @property
    def cache_event_listener(self):
        return self.pool.cache_event_listener

    @cache_event_listener.setter
    def cache_event_listener(self, fn):
        self.pool.cache_event_listener = fn

    @property
    def cache(self):
        return self.runner.cache

    @cache.setter
    def cache(self, value):
        self.runner.cache = value

    @property
    def W(self):
        return self.runner.W

    @property
    def use_kernel(self):
        return self.runner.use_kernel

    @property
    def kv_quant(self):
        return self.runner.kv_quant

    @property
    def _decode_programs(self):
        return self.runner._decode_programs

    @property
    def _verify_programs(self):
        return self.runner._verify_programs
