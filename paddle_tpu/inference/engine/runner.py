"""Device half of the engine core: weights, paged-KV buffers, and the jitted
prefill / decode / verify programs, all pinned to ONE mesh slice.

:class:`ModelRunner` owns everything that lives on (or dispatches to) the
accelerator: the stacked ``[L, ...]`` weight arrays with their pp×mp
NamedShardings, the paged KV cache arrays, the per-shape jitted program
caches, the copy-on-write device page copy, and the page gather/scatter
primitives the disaggregated engine's KV handoff is built from.  It holds NO
scheduling state — no queues, no refcounts, no request objects — so two
runners over disjoint mesh slices (prefill vs decode) can serve one logical
engine.

TPU-native design (carried over from the monolithic serving engine):
- TWO jitted programs serve a colocated engine: a PREFILL step consuming a
  CHUNK of prompt tokens for one slot per dispatch (chunk rows ride the
  paged-attention kernel's batch dim with per-row context lengths, so causal
  masking falls out of ctx=pos+1), and a DECODE step feeding every in-flight
  slot its last token — token-level continuous batching (Orca-style).  A
  third VERIFY program scores K+1 consecutive positions per request for
  speculative decoding.
- Sampling happens IN-GRAPH with per-slot parameters (greedy / temperature /
  top-k / top-p / seed), replicating models.llama._sample token-for-token.
- KV lives in PAGES [L, n_pages, page, KVH, D]; page tables arrive from the
  scheduler per dispatch.  Pages are just indices here — allocation policy
  (refcounts, prefix cache, preemption) is the PagePool's business.
- Weights are extracted from the model once, stacked [L, ...] and placed
  with NamedShardings: layers sharded over the pp axis, head/ffn dims over
  the mp axis. GSPMD inserts the collectives.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ModelRunner"]

_MAXK = 64        # static cap for per-slot dynamic top-k filtering


def _rope(x, pos, theta):
    """neox-style RoPE at integer positions pos [B] (x [B, Hn, D])."""
    D = x.shape[-1]
    inv = 1.0 / (theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    freqs = pos.astype(jnp.float32)[:, None] * inv[None, :]      # [B, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)               # [B, D]
    s, c = jnp.sin(emb)[:, None, :], jnp.cos(emb)[:, None, :]
    xf = x.astype(jnp.float32)
    half = D // 2
    rot = jnp.concatenate([-xf[..., half:], xf[..., :half]], axis=-1)
    return (xf * c + rot * s).astype(x.dtype)


def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32)).astype(
        x.dtype)


def _sample_row(logits, greedy, temp, topp, topk, seed):
    """One row of in-graph sampling, replicating models.llama._sample +
    ops.top_p_sampling (same filter order, same sort, same categorical
    key/shape) so a SEEDED top_p<1 engine decode == model.generate.
    (At top_p>=1.0, generate falls through to ops.multinomial on the global
    RNG stream, which ignores the seed — no parity is possible there by
    construction.) logits [V] f32; scalars traced."""
    maxk = min(_MAXK, logits.shape[-1])
    amax = jnp.argmax(logits)
    l = logits / jnp.where(temp > 0, temp, 1.0)
    probs = jax.nn.softmax(l)
    # top-k (0 = off): zero everything below the k-th largest prob
    kvals, _ = jax.lax.top_k(probs, maxk)
    thresh = kvals[jnp.clip(topk - 1, 0, maxk - 1)]
    probs = jnp.where((topk > 0) & (probs < thresh), 0.0, probs)
    probs = probs / jnp.sum(probs)
    # top-p over the full sorted vocab (ops.top_p_sampling's formulation)
    sort_idx = jnp.argsort(-probs)
    sorted_p = probs[sort_idx]
    cum = jnp.cumsum(sorted_p)
    keep = jnp.where(topp < 1.0, (cum - sorted_p) < topp, sorted_p >= 0)
    filtered = jnp.where(keep, sorted_p, 0.0)
    filtered = filtered / jnp.sum(filtered)
    key = jax.random.PRNGKey(seed)
    # [1, V] shape matches the b=1 categorical in ops.top_p_sampling, so the
    # gumbel draw is bit-identical at equal keys
    choice = jax.random.categorical(
        key, jnp.log(jnp.maximum(filtered, 1e-30))[None, :], axis=-1)[0]
    tok = sort_idx[choice]
    return jnp.where(greedy > 0, amax, tok).astype(jnp.int32)


class ModelRunner:
    """Weights + paged KV + jitted forwards over one mesh (slice)."""

    def __init__(self, model, mesh=None, mp_axis="mp", pp_axis="pp",
                 max_batch=4, page_size=16, prefill_chunk=32, n_pages=None,
                 use_kernel=None, kv_cache_dtype="auto"):
        cfg = model.config
        self.cfg = cfg
        self.mesh = mesh
        self.max_batch = int(max_batch)
        self.page = int(page_size)
        self.chunk = int(prefill_chunk)
        self.n_pages = int(n_pages)
        self.trash_page = self.n_pages - 1
        L = cfg.num_hidden_layers
        H = cfg.hidden_size
        nh, kvh = cfg.num_attention_heads, cfg.num_key_value_heads
        D = H // nh
        self.nh, self.kvh, self.D = nh, kvh, D
        if use_kernel is None:
            use_kernel = (mesh is None and
                          jax.devices()[0].platform in ("tpu", "axon"))
        self.use_kernel = use_kernel

        def wb(lin):        # Linear stores weight [in, out]
            return np.asarray(lin.weight._data)

        lay = model.llama.layers
        W = {
            "embed": np.asarray(model.llama.embed_tokens.weight._data),
            "norm": np.asarray(model.llama.norm.weight._data),
            "wq": np.stack([wb(l.self_attn.q_proj) for l in lay]),
            "wk": np.stack([wb(l.self_attn.k_proj) for l in lay]),
            "wv": np.stack([wb(l.self_attn.v_proj) for l in lay]),
            "wo": np.stack([wb(l.self_attn.o_proj) for l in lay]),
            "ln1": np.stack([np.asarray(l.input_layernorm.weight._data)
                             for l in lay]),
            "ln2": np.stack([np.asarray(
                l.post_attention_layernorm.weight._data) for l in lay]),
            "wg": np.stack([wb(l.mlp.gate_proj) for l in lay]),
            "wu": np.stack([wb(l.mlp.up_proj) for l in lay]),
            "wd": np.stack([wb(l.mlp.down_proj) for l in lay]),
        }
        W["head"] = (np.asarray(model.lm_head.weight._data)
                     if model.lm_head is not None else W["embed"].T)
        dtype = W["wq"].dtype
        if mesh is not None:
            pp = pp_axis if pp_axis in mesh.axis_names else None
            mp = mp_axis if mp_axis in mesh.axis_names else None

            def put(name, arr, spec):
                return jax.device_put(jnp.asarray(arr),
                                      NamedSharding(mesh, spec))
            specs = {
                "embed": P(), "norm": P(), "head": P(None, mp),
                "wq": P(pp, None, mp), "wk": P(pp, None, mp),
                "wv": P(pp, None, mp), "wo": P(pp, mp, None),
                "ln1": P(pp, None), "ln2": P(pp, None),
                "wg": P(pp, None, mp), "wu": P(pp, None, mp),
                "wd": P(pp, mp, None),
            }
            self.W = {k: put(k, v, specs[k]) for k, v in W.items()}
            cache_spec = NamedSharding(mesh, P(pp))
        else:
            self.W = {k: jnp.asarray(v) for k, v in W.items()}
            cache_spec = None
        self.cache_sharding = cache_spec
        self.kv_quant = (kv_cache_dtype == "int8")
        page_dtype = jnp.int8 if self.kv_quant else dtype
        kp = jnp.zeros((L, self.n_pages, page_size, kvh, D), page_dtype)
        vp = jnp.zeros_like(kp)
        if cache_spec is not None:
            kp = jax.device_put(kp, cache_spec)
            vp = jax.device_put(vp, cache_spec)
        if self.kv_quant:
            ks = jnp.zeros((L, self.n_pages, page_size, kvh), jnp.float32)
            vs = jnp.zeros_like(ks)
            if cache_spec is not None:
                ks = jax.device_put(ks, cache_spec)
                vs = jax.device_put(vs, cache_spec)
            self.cache = (kp, vp, ks, vs)
        else:
            self.cache = (kp, vp)
        self._prefill = self._build_prefill()
        self._decode_programs: dict = {}
        self._verify_programs: dict = {}
        self._copy_page_fn = None
        self._gather_fn = {}
        self._scatter_fn = {}

    @property
    def devices(self):
        """The device set this runner's buffers live on."""
        if self.mesh is not None:
            return tuple(self.mesh.devices.reshape(-1))
        return (jax.devices()[0],)

    # ---------------------------------------------------------------- layers
    def _layer_fn(self, page_idx, within, tables, ctx, pos, mq=None):
        """Shared per-layer body for decode, prefill, and speculative
        verification (they differ only in how many rows ride the batch dim
        and where those rows' pages are). With ``mq=(B, Q)`` the flat rows
        are B sequences x Q consecutive query positions and attention goes
        through the multi-query kernel (tables [B, S]; ctx [B] is row 0's
        context length, row j sees ctx+j); KV writes stay per-flat-row."""
        nh, kvh, D = self.nh, self.kvh, self.D
        eps = self.cfg.rms_norm_eps
        theta = self.cfg.rope_theta
        use_kernel = self.use_kernel

        quant = self.kv_quant

        def layer(carry, wl):
            from ...ops.pallas.paged_attention import (
                paged_attention, paged_attention_multiquery,
                paged_attention_multiquery_ref, paged_attention_ref,
                quantize_kv)
            x, = carry
            h = _rms(x, wl["ln1"], eps)
            q = (h @ wl["wq"]).reshape(-1, nh, D)
            k = (h @ wl["wk"]).reshape(-1, kvh, D)
            v = (h @ wl["wv"]).reshape(-1, kvh, D)
            q = _rope(q, pos, theta)
            k = _rope(k, pos, theta)
            if mq is None:
                attn = paged_attention if use_kernel else paged_attention_ref
            else:
                Bq, Q = mq
                base = (paged_attention_multiquery if use_kernel
                        else paged_attention_multiquery_ref)

                def attn(qx, kp, vp, tb, cl, **kw):
                    out = base(qx.reshape(Bq, Q, nh, D), kp, vp, tb, cl,
                               **kw)
                    return out.reshape(Bq * Q, nh, D)
            if quant:
                kq, ksc = quantize_kv(k)
                vq, vsc = quantize_kv(v)
                kpl = wl["kp"].at[page_idx, within].set(kq)
                vpl = wl["vp"].at[page_idx, within].set(vq)
                ksl = wl["kps"].at[page_idx, within].set(ksc)
                vsl = wl["vps"].at[page_idx, within].set(vsc)
                att = attn(q, kpl, vpl, tables, ctx,
                           k_scales=ksl, v_scales=vsl)
                new_cache = (kpl, vpl, ksl, vsl)
            else:
                kpl = wl["kp"].at[page_idx, within].set(k)
                vpl = wl["vp"].at[page_idx, within].set(v)
                att = attn(q, kpl, vpl, tables, ctx)
                new_cache = (kpl, vpl)
            x = x + att.reshape(-1, nh * D) @ wl["wo"]
            h = _rms(x, wl["ln2"], eps)
            gate = h @ wl["wg"]
            up = h @ wl["wu"]
            x = x + (jax.nn.silu(gate.astype(jnp.float32)).astype(
                up.dtype) * up) @ wl["wd"]
            return (x,), new_cache

        return layer

    def _scan_layers(self, W, cache, x, layer):
        per_layer = {k: W[k] for k in
                     ("wq", "wk", "wv", "wo", "ln1", "ln2",
                      "wg", "wu", "wd")}
        per_layer["kp"], per_layer["vp"] = cache[0], cache[1]
        if len(cache) == 4:
            per_layer["kps"], per_layer["vps"] = cache[2], cache[3]
        (x,), new_cache = jax.lax.scan(layer, (x,), per_layer)
        return x, new_cache

    # ------------------------------------------------------------- programs
    def _build_decode(self, K):
        """K decode steps fused into ONE dispatch (token feedback stays
        in-graph via lax.scan) — through a remote dispatch path each host
        round trip costs RTT, which a per-token loop pays in full; a K-block
        pays RTT/K. The host sees the K sampled tokens afterwards, so eos
        requests cap K at 1 (every token must be inspected). Mirrors
        generate()'s tokens_per_dispatch."""
        page = self.page
        eps = self.cfg.rms_norm_eps
        trash = self.trash_page

        def block(W, cache, tokens, lens, tables, active,
                  greedy, temp, topp, topk, seeds, fold):
            # tokens [B] int32; lens [B] tokens already cached; tables
            # [B, S] page ids; active [B] 0/1; sampling params [B].
            # fold [B]: 1 -> vary the sampling key per block step (seedless
            # requests); 0 -> reuse it (fixed-seed generate parity).
            def one(carry, i):
                tokens, lens, cache = carry
                x = W["embed"][tokens]                   # [B, H]
                pos = lens.astype(jnp.int32)
                page_idx = jnp.take_along_axis(
                    tables, (pos // page)[:, None], axis=1)[:, 0]
                # inactive slots write into the trash page, never a live one
                page_idx = jnp.where(active > 0, page_idx, trash)
                within = pos % page
                ctx = jnp.where(active > 0, pos + 1, 1).astype(jnp.int32)
                layer = self._layer_fn(page_idx, within, tables, ctx, pos)
                x, cache = self._scan_layers(W, cache, x, layer)
                h = _rms(x, W["norm"], eps)
                logits = h.astype(jnp.float32) @ W["head"].astype(
                    jnp.float32)
                # one vmapped sampler, not B inlined sort/cumsum subgraphs
                nxt = jax.vmap(_sample_row)(logits, greedy, temp, topp,
                                            topk, seeds + i * fold)
                tokens = jnp.where(active > 0, nxt, tokens)
                lens = lens + (active > 0).astype(lens.dtype)
                return (tokens, lens, cache), nxt

            (_, _, cache2), toks = jax.lax.scan(
                one, (tokens, lens, cache),
                jnp.arange(K, dtype=jnp.int32))
            return toks, cache2                          # toks [K, B]

        return jax.jit(block, donate_argnums=(1,))

    def _build_prefill(self):
        page = self.page
        eps = self.cfg.rms_norm_eps
        trash = self.trash_page
        C = self.chunk

        def prefill(W, cache, tokens, start, table, n_valid,
                    greedy, temp, topp, topk, seed):
            # tokens [C] int32 (one slot's prompt chunk, zero-padded);
            # start scalar; table [S]; n_valid scalar <= C. Chunk rows ride
            # the paged-attention BATCH dim: row i gets ctx = start+i+1, so
            # in-chunk causality and attention to the already-cached prefix
            # both fall out of the per-row context length.
            x = W["embed"][tokens]                       # [C, H]
            offs = jnp.arange(C, dtype=jnp.int32)
            pos = start.astype(jnp.int32) + offs
            valid = offs < n_valid
            page_idx = table[pos // page]
            page_idx = jnp.where(valid, page_idx, trash)
            within = pos % page
            ctx = jnp.where(valid, pos + 1, 1).astype(jnp.int32)
            tables = jnp.broadcast_to(table[None, :], (C, table.shape[0]))
            layer = self._layer_fn(page_idx, within, tables, ctx, pos)
            x, cache2 = self._scan_layers(W, cache, x, layer)
            h = _rms(x, W["norm"], eps)
            last = h[jnp.maximum(n_valid - 1, 0)]
            logits = last.astype(jnp.float32) @ W["head"].astype(jnp.float32)
            nxt = _sample_row(logits, greedy, temp, topp, topk, seed)
            return nxt, cache2

        return jax.jit(prefill, donate_argnums=(1,))

    def _build_verify(self, Kv):
        """ONE forward scoring Kv consecutive positions per request — the
        speculative-decoding verifier. Row 0 carries the pending token
        (what plain decode would feed), rows 1..n the proposed drafts;
        sampling row j yields the target model's token AFTER draft j, so
        the host accepts the longest draft prefix matching the sampled
        tokens and emits accepted+1 tokens from a single dispatch. All Kv
        KV writes land in-graph; the host rolls back pages past the
        accepted point afterwards (attention masks by context length, so
        stale writes beyond a slot's length are never attended)."""
        page = self.page
        eps = self.cfg.rms_norm_eps
        trash = self.trash_page
        B = self.max_batch

        def verify(W, cache, tokens, lens, tables, n_rows,
                   greedy, temp, topp, topk, seeds, fold):
            # tokens [B, Kv] int32 (row 0 = pending, 1.. = drafts, rest
            # padding); lens [B] tokens already cached; n_rows [B] valid
            # rows (0 = inactive slot); sampling params [B] as in decode.
            row_j = jnp.tile(jnp.arange(Kv, dtype=jnp.int32), B)  # [B*Kv]

            def rep(a):
                return jnp.repeat(a, Kv)

            pos = rep(lens.astype(jnp.int32)) + row_j
            valid = row_j < rep(n_rows)
            page_idx = jnp.take_along_axis(
                tables, (pos // page).reshape(B, Kv), axis=1).reshape(-1)
            page_idx = jnp.where(valid, page_idx, trash)
            within = pos % page
            # row 0 of an active request sees lens+1 tokens (its own write
            # included); the multi-query kernel extends by +j per row
            cl = jnp.where(n_rows > 0, lens + 1, 1).astype(jnp.int32)
            x = W["embed"][tokens.reshape(-1)]            # [B*Kv, H]
            layer = self._layer_fn(page_idx, within, tables, cl, pos,
                                   mq=(B, Kv))
            x, cache2 = self._scan_layers(W, cache, x, layer)
            h = _rms(x, W["norm"], eps)
            logits = h.astype(jnp.float32) @ W["head"].astype(jnp.float32)
            # seed schedule mirrors the decode block's `seeds + i*fold`:
            # emitted token #j of this step draws the key step #j of a
            # non-speculative block would have drawn, so fixed-seed
            # (fold=0) and greedy requests stay token-exact vs spec-off
            seeds_rep = rep(seeds) + row_j * rep(fold)
            toks = jax.vmap(_sample_row)(
                logits, rep(greedy), rep(temp), rep(topp), rep(topk),
                seeds_rep)
            return toks.reshape(B, Kv), cache2

        return jax.jit(verify, donate_argnums=(1,))

    # ------------------------------------------------------------- dispatch
    def has_decode_program(self, k):
        return k in self._decode_programs

    def has_verify_program(self, kv):
        return kv in self._verify_programs

    def run_prefill(self, tokens, start, table, n_valid,
                    greedy, temp, topp, topk, seed):
        """Dispatch one prefill chunk; returns the sampled next token as a
        DEVICE value (only the caller decides whether to sync on it — a
        mid-prompt chunk's sample is never read)."""
        nxt, self.cache = self._prefill(
            self.W, self.cache, jnp.asarray(tokens),
            jnp.asarray(np.int32(start)), jnp.asarray(table),
            jnp.asarray(np.int32(n_valid)),
            jnp.asarray(np.int32(greedy)), jnp.asarray(np.float32(temp)),
            jnp.asarray(np.float32(topp)), jnp.asarray(np.int32(topk)),
            jnp.asarray(np.int32(seed)))
        return nxt

    def run_decode(self, k, tokens, lens, tables, active,
                   greedy, temp, topp, topk, seeds, fold):
        """Dispatch one K-token decode block; returns host tokens [k, B]
        (the np.asarray sync makes the caller's wall time a true dispatch
        sample)."""
        prog = self._decode_programs.get(k)
        if prog is None:
            prog = self._decode_programs[k] = self._build_decode(k)
        toks, self.cache = prog(
            self.W, self.cache, jnp.asarray(tokens), jnp.asarray(lens),
            jnp.asarray(tables), jnp.asarray(active), jnp.asarray(greedy),
            jnp.asarray(temp), jnp.asarray(topp), jnp.asarray(topk),
            jnp.asarray(seeds), jnp.asarray(fold))
        return np.asarray(toks)

    def run_verify(self, kv, tokens, lens, tables, n_rows,
                   greedy, temp, topp, topk, seeds, fold):
        """Dispatch one speculative verify step; returns host tokens
        [B, Kv]."""
        prog = self._verify_programs.get(kv)
        if prog is None:
            prog = self._verify_programs[kv] = self._build_verify(kv)
        toks, self.cache = prog(
            self.W, self.cache, jnp.asarray(tokens), jnp.asarray(lens),
            jnp.asarray(tables), jnp.asarray(n_rows), jnp.asarray(greedy),
            jnp.asarray(temp), jnp.asarray(topp), jnp.asarray(topk),
            jnp.asarray(seeds), jnp.asarray(fold))
        return np.asarray(toks)

    # ---------------------------------------------------------- page movement
    def copy_page(self, src, dst):
        """Device-side copy of one physical KV page (all layers, K and V,
        int8 scales included) — the copy half of copy-on-write."""
        if self._copy_page_fn is None:
            def cp(cache, s, d):
                return tuple(a.at[:, d].set(a[:, s]) for a in cache)
            self._copy_page_fn = jax.jit(cp, donate_argnums=(0,))
        self.cache = self._copy_page_fn(
            self.cache, jnp.asarray(np.int32(src)), jnp.asarray(np.int32(dst)))

    def gather_pages(self, page_idx):
        """Pull ``page_idx`` pages out of the cache as a dense block (tuple
        of [L, n, page, ...] arrays) — the send half of a cross-slice KV
        handoff.  The gather is jitted per block size so repeated handoffs
        at one size reuse the program."""
        n = len(page_idx)
        fn = self._gather_fn.get(n)
        if fn is None:
            def gather(cache, idx):
                return tuple(a[:, idx] for a in cache)
            fn = self._gather_fn[n] = jax.jit(gather)
        return fn(self.cache, jnp.asarray(np.asarray(page_idx, np.int32)))

    def scatter_pages(self, page_idx, block):
        """Write a dense page block into ``page_idx`` of this runner's cache
        — the receive half of a cross-slice KV handoff.  The cache buffers
        are donated, so the write is in-place where XLA allows."""
        n = len(page_idx)
        fn = self._scatter_fn.get(n)
        if fn is None:
            def scatter(cache, blk, idx):
                return tuple(a.at[:, idx].set(b) for a, b in zip(cache, blk))
            fn = self._scatter_fn[n] = jax.jit(scatter, donate_argnums=(0,))
        self.cache = fn(self.cache, block,
                        jnp.asarray(np.asarray(page_idx, np.int32)))

    def kv_bytes_per_page(self):
        """HBM bytes one KV page costs across all layers (both K and V,
        including int8 scales) — the unit of the page_pool budget."""
        return sum(int(a.nbytes) for a in self.cache) // self.n_pages

    def pages_to_host(self, page_idx):
        """Gather ``page_idx`` pages and land them in host RAM as a tuple of
        owned numpy arrays (one [L, n, page, ...] array per cache component)
        — the device half of a host-tier spill.  Uses the checkpoint
        snapshot idiom: start the non-blocking device→host DMA first, then
        materialize owned copies (np.array, never a view) so the block
        outlives any later donation of the cache buffers."""
        blk = self.gather_pages(page_idx)
        for a in blk:
            try:
                a.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass                      # older arrays: np.array blocks
        return tuple(np.array(a) for a in blk)

    def put_block(self, block):
        """Start the transfer of a gathered page block (device arrays from
        another runner's ``gather_pages``, or host numpy arrays off the
        RPC plane) onto THIS runner's cache sharding.  ``device_put`` is
        asynchronous — the returned arrays are in flight and a subsequent
        ``scatter_pages`` chains on them, so the copy overlaps whatever
        the caller dispatches in between."""
        dst = self.cache_sharding if self.cache_sharding is not None \
            else self.devices[0]
        return tuple(jax.device_put(a, dst) for a in block)

    def restore_pages(self, page_idx, host_blocks):
        """Write host-tier page blocks back into device pages ``page_idx``
        (one single-page block per entry, in order) — the device half of a
        spill restore.  Double-buffered: page i+1's host→device transfer is
        issued before page i's scatter is dispatched, so the copy hides
        behind the previous write."""
        if not page_idx:
            return
        pending = jax.device_put(host_blocks[0])
        for i, p in enumerate(page_idx):
            blk, pending = pending, (
                jax.device_put(host_blocks[i + 1])
                if i + 1 < len(page_idx) else None)
            self.scatter_pages([p], blk)
