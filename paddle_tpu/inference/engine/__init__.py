"""Engine core as a package: the serving monolith split along its natural
interfaces.

Layering (each module imports only what is below it; the import-cycle
guard in ``tests/test_analysis.py`` enforces this):

    request    Request / RequestStatus / prefix_page_keys — lifecycle types
    metrics    _EngineMetrics — per-engine registry children (labelled)
    compat     _LegacyDelegation — the pre-split private-attribute surface
    pages      PagePool — paged-KV accounting: refcounts, prefix-cache
               chain-hash index, LRU reclaim, audit
    runner     ModelRunner — the jitted prefill/decode/verify programs and
               the KV buffers over ONE mesh (slice), plus page gather/
               scatter for cross-slice handoff
    spec       SpecConfig, the draft proposers, and the engine's
               speculative-decode orchestration mixin
    scheduler  Scheduler — admission, deadlines, continuous batching,
               preemption, slot/page-table state
    core       LLMEngine — the facade composing the above; owns step
               policy, failure isolation, and the auto-fits
    disagg     DisaggEngine — prefill and decode LLMEngines on separate
               mesh slices with KV-page handoff between their pools

``paddle_tpu.inference.serving`` re-exports the public names, so existing
imports keep working unchanged.
"""
from .request import Request, RequestStatus, prefix_page_keys
from .pages import PagePool
from .runner import ModelRunner
from .spec import SpecConfig
from .scheduler import Scheduler
from .core import LLMEngine
from .disagg import DisaggEngine, split_mesh

__all__ = [
    "LLMEngine", "DisaggEngine", "split_mesh",
    "Scheduler", "PagePool", "ModelRunner",
    "Request", "RequestStatus", "SpecConfig", "prefix_page_keys",
]
