"""Request lifecycle types shared by every engine-core module.

This is the bottom of the engine package's layering: ``request`` imports
nothing from its siblings (``pages``, ``scheduler``, ``runner``, ``core``,
``disagg``) — the import-cycle guard in ``tests/test_analysis.py`` keeps it
that way.
"""
from __future__ import annotations

import enum
import time

import numpy as np

__all__ = ["Request", "RequestStatus", "prefix_page_keys"]


def prefix_page_keys(tokens, page_size):
    """Chain key per FULL page: key_i = hash(key_{i-1}, page_i tokens).

    The prefix-cache radix lookup collapsed to one dict probe per page — a
    page is shareable only as the tail of an identical-from-position-0
    prefix (RoPE bakes absolute positions into cached K, so content alone
    is not enough).  Public because the serving front door computes the
    SAME keys to route a request to the replica whose cache already holds
    its prefix (frontend/router.py); the engine's own radix index uses
    this function too, so router affinity and engine hits can never
    disagree on hashing."""
    page_size = int(page_size)
    keys, h = [], None
    for i in range(0, (len(tokens) // page_size) * page_size, page_size):
        h = hash((h,) + tuple(int(t) for t in tokens[i:i + page_size]))
        keys.append(h)
    return keys


class RequestStatus(enum.Enum):
    """Request lifecycle. Exactly one terminal status per request:

    FINISHED   max_new_tokens (or engine max_len) reached
    EOS        the eos token was sampled
    TIMEOUT    deadline expired (waiting: shed unserved; mid-decode: the
               partial output is kept and the slot finalized cleanly)
    CANCELLED  ``cancel(rid)`` — pages released through the refcounts
    SHED       admission control refused the request at add_request
    FAILED     quarantined by step-failure isolation (``Request.error`` holds
               the underlying exception text)
    """
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    EOS = "eos"
    TIMEOUT = "timeout"
    CANCELLED = "cancelled"
    SHED = "shed"
    FAILED = "failed"

    @property
    def terminal(self):
        return self not in (RequestStatus.QUEUED, RequestStatus.RUNNING)


TERMINAL_STATUSES = tuple(s for s in RequestStatus if s.terminal)


class Request:
    def __init__(self, rid, prompt_ids, max_new_tokens, eos_token_id=None,
                 do_sample=False, temperature=1.0, top_p=1.0, top_k=0,
                 seed=None, deadline=None, resume_tokens=None):
        """``resume_tokens``: output history from a previous incarnation of
        this request (a replica that died mid-stream).  The history folds
        into the prompt exactly like preemption folds ``prompt0 + out`` —
        it re-prefills as context, the first token sampled here continues
        the sequence, and ``out`` holds only NEW tokens so the streaming
        accessors never re-emit what the caller already has."""
        self.rid = rid
        self.prompt = list(int(t) for t in np.asarray(prompt_ids).reshape(-1))
        self.resumed_from = 0
        if resume_tokens is not None:
            resume = [int(t) for t in resume_tokens]
            self.prompt += resume
            self.resumed_from = len(resume)
        self.prompt0 = list(self.prompt)   # original; preemption re-folds
        self.max_new = int(max_new_tokens)
        self.eos = eos_token_id
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.top_k = int(top_k)
        self.seed = seed
        self.out: list[int] = []
        self.pos = 0                 # prompt tokens already prefilled
        self.slot = None
        self.done = False
        self.admit_seq = -1          # preemption picks the youngest
        self.t_submit = time.perf_counter()
        # absolute wall deadline; expiry sheds a waiting request and cleanly
        # finalizes a decoding one (both terminal status TIMEOUT)
        self.deadline = (None if deadline is None
                         else self.t_submit + float(deadline))
        self.status = RequestStatus.QUEUED
        self.error = None            # exception text when status is FAILED
        self.t_finish = None
        self.ttft = None             # seconds to first generated token
        self.prefill_dispatches = 0  # prefill programs dispatched for us
        self.cached_tokens = 0       # prompt tokens served from prefix cache
        self.cache_keys = ()         # chain keys of the prompt's full pages
        self.stream_pos = 0          # tokens already handed to new_tokens()
        self.trace_id = None         # flight-recorder trace (ambient ctx)
