""":class:`LLMEngine` — the engine-core facade.

The engine composes the three core components behind explicit interfaces —
:class:`~.scheduler.Scheduler` (admission, deadlines, continuous batching,
preemption), :class:`~.pages.PagePool` (paged KV accounting, refcounts,
prefix cache, CoW, rollback), :class:`~.runner.ModelRunner` (prefill /
decode / verify forwards over a mesh slice) — and keeps the pre-split
public API byte-for-byte: the frontend, fault-tolerance, and spec-decode
layers drive it unchanged, and the legacy private attributes
(``_slots``, ``_free_pages``, ``_waiting``, ...) remain reachable through
the :class:`~.compat._LegacyDelegation` mixin.

What stays IN the facade is exactly the cross-component orchestration: the
step loop and its phase policy, step-failure isolation (transient retry →
quarantine bisection), the decode-block auto-fit, metrics, and the
streaming accessors (speculative accept/rollback rides in via
:class:`~.spec._SpecOrchestration`).  The
``prefill_sink`` hook is the disaggregation seam: when set, a request whose
prompt just finished prefilling is handed to the sink (which detaches it
for KV handoff) instead of entering this engine's decode phase — see
:class:`~.disagg.DisaggEngine`.
"""
from __future__ import annotations

import math
import time

import numpy as np
import jax.numpy as jnp  # noqa: F401  (re-exported for monkeypatch parity)

from ... import observability as _obs
from ...observability import flight as _flight
from ...core.retry import RetryError, RetryPolicy, retry_call
from ...testing.faults import FAULTS as _faults
from .compat import _LegacyDelegation
from .metrics import _EngineMetrics
from .pages import HostPageStore, PagePool
from .request import Request, RequestStatus
from .runner import _MAXK, ModelRunner
from .scheduler import Scheduler
from .spec import _SpecOrchestration

__all__ = ["LLMEngine"]


class _TransientStep(Exception):
    """Private wrapper around a transient step error so :func:`retry_call`
    retries exactly those — any non-transient error escapes the retry loop
    unwrapped and falls through to quarantine isolation."""

    def __init__(self, err):
        super().__init__(str(err))
        self.err = err


class _TransientTier(Exception):
    """Private wrapper around a transient KV-tier error (``kv.spill`` /
    ``kv.restore`` fault points) so :func:`retry_call` retries exactly
    those; a poison (non-transient) error escapes the retry loop and the
    tier operation degrades to its lossless fallback — eviction on spill,
    recompute on restore."""

    def __init__(self, err):
        super().__init__(str(err))
        self.err = err


class LLMEngine(_LegacyDelegation, _SpecOrchestration):
    """Continuous-batching paged-KV engine over a LlamaForCausalLM.

    The pre-split private-attribute surface comes from
    :class:`~.compat._LegacyDelegation`; the speculative-decode
    orchestration from :class:`~.spec._SpecOrchestration`."""

    _engine_seq = 0   # observability label: one series set per engine

    def __init__(self, model, mesh=None, mp_axis="mp", pp_axis="pp",
                 max_batch=4, max_len=256, page_size=16, prefill_chunk=32,
                 page_pool=None, decode_block=1, use_kernel=None, seed=0,
                 kv_cache_dtype="auto", decode_block_max=32,
                 prefix_cache=False, spec_decode=None, max_waiting=None,
                 shed_min_free_ratio=0.0, default_deadline=None,
                 step_retry=None, debug_refcount_audit=False,
                 host_cache_bytes=None):
        """page_pool: usable KV pages (the HBM budget). Defaults to the
        worst case (max_batch * ceil(max_len/page)); set it SMALLER to
        oversubscribe — on-demand growth means slots only claim what they
        use, and a dry pool preempts the youngest slot (recompute).

        prefix_cache: automatic prefix caching (vLLM shared pages + CoW,
        SGLang-style chain-hash lookup). Full prompt pages are hashed by
        (prefix chain, page tokens) and refcounted; a later request whose
        prompt starts with a cached page chain maps those physical pages
        into its table and skips their prefill entirely (at least the final
        prompt token always re-prefills — its logits sample the first output
        token, and when that token's page is still shared the write goes
        through a copy-on-write private page). Released-but-cached pages
        park in an LRU and are evicted only when the free list runs dry.
        Counters: ``cache_hits`` / ``cache_misses`` (pages, at admission),
        ``cache_evictions``, ``cache_cow_copies`` — see
        :meth:`prefix_cache_stats`. Token streams are byte-identical to a
        ``prefix_cache=False`` engine at the same seeds; only dispatch
        counts and TTFT change. (One caveat shared with generate(): a
        do_sample request WITHOUT a fixed seed draws from the engine's
        global seed counter, which advances once per prefill dispatch —
        fewer dispatches shift later seedless draws. Seeded and greedy
        requests are unaffected.)

        decode_block: max decode steps fused into one dispatch (power-of-two
        blocks are chosen per step, shrinking near max_new; eos-bearing
        requests force 1). Raise it when dispatch latency, not throughput,
        dominates (e.g. a remote/tunneled runtime) — or pass "auto": the
        engine then samples wall time at two block sizes, solves the
        dispatch model t(k) = RTT + k*c for the session's actual round-trip
        latency and per-token device time, and picks the power-of-two block
        where RTT costs <= ~25% of device time (re-estimated as timing
        samples accumulate, capped at decode_block_max).

        kv_cache_dtype: "auto" stores pages in the weight dtype; "int8"
        quantizes K/V pages per-(token, kv-head) with f32 scales (reference:
        incubate block_multihead_attention cache_*_quant_scales, dynamic
        mode) — pages cost (D + 4)/(2*D) of bf16 bytes (~0.52 at
        head_dim=128), so the same HBM budget holds ~2x the tokens /
        concurrent slots.

        spec_decode: a :class:`SpecConfig` enables speculative decoding —
        each step a proposer drafts up to max_draft continuation tokens per
        request (self-drafting n-gram suffix match by default, or a small
        draft model) and ONE target-model forward scores the pending token
        plus every draft at consecutive positions (multi-query paged
        attention). Acceptance is the standard token-match rule — the
        longest draft prefix that equals what the target would have
        sampled — which for the deterministic proposers here is exact
        rejection sampling, so greedy and fixed-seed sampled outputs are
        token-identical to a spec-off engine. Accepted tokens all land in
        one dispatch (up to max_draft+1 tokens/step); rejected drafts roll
        their provisional KV pages back through the page-pool refcounts
        (a partially-filled page is truncated, never shared). Steps where
        no request has a draft fall through to the normal decode-block
        path. Counters: :meth:`spec_stats`, plus ``spec_proposed_total`` /
        ``spec_accepted_total`` / acceptance histogram in the registry.

        Fault tolerance (see :meth:`health` for the counter snapshot):

        max_waiting: admission-control queue bound — add_request beyond it
        returns a request already terminal with status SHED (None keeps the
        legacy unbounded queue).
        shed_min_free_ratio: page-pressure watermark — while the backlog is
        non-empty and (free + reclaimable) pages fall below this fraction of
        the pool, new requests are shed.
        default_deadline: seconds each request may spend end-to-end unless
        add_request overrides; expiry sheds waiting requests and cleanly
        finalizes decoding ones (status TIMEOUT, partial output kept).
        step_retry: :class:`~paddle_tpu.core.retry.RetryPolicy` for
        TRANSIENT step errors (an exception with a truthy ``transient``
        attribute, e.g. an injected transient fault) — the step is retried
        with backoff before failure isolation kicks in. Default: 3 attempts,
        10ms base.  Non-transient step errors never crash the loop: the
        failing dispatch is re-run one slot at a time and the slot that
        fails alone is quarantined (terminal FAILED, pages freed through the
        refcounts) while the rest keep serving.
        debug_refcount_audit: run :meth:`audit_refcounts` after every step
        and raise on any page-accounting violation (tier-1 chaos tests keep
        this on to prove no failure path leaks pages).

        host_cache_bytes: byte budget for the host-RAM KV spill tier
        (requires ``prefix_cache``).  When set, LRU reclaim and preemption
        demote page contents to host RAM (async device→host copy) instead
        of discarding them, and an admission hit against a spilled chain
        restores the pages via double-buffered host→device prefetch instead
        of re-prefilling.  The tier has its own LRU within the budget;
        every tier path is lossless-on-failure (spill failure → plain
        eviction, restore failure → recompute) and token-exact vs the
        recompute path.  Counters: :meth:`kv_tier_stats`; fault points:
        ``kv.spill`` / ``kv.restore``."""
        cfg = model.config
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len
        self.page = page_size
        self.chunk = int(prefill_chunk)
        self.pages_per_slot = math.ceil(max_len / page_size)
        if page_pool is None:
            page_pool = max_batch * self.pages_per_slot
        if page_pool < self.pages_per_slot:
            raise ValueError("page_pool must cover at least one max_len "
                             f"request ({self.pages_per_slot} pages)")
        # +1: a trash page absorbing the (masked-out) writes of inactive slots
        self.n_pages = int(page_pool) + 1
        self.trash_page = self.n_pages - 1
        self.mesh = mesh
        self.prefix_cache = bool(prefix_cache)
        self._m = _EngineMetrics(str(LLMEngine._engine_seq))
        LLMEngine._engine_seq += 1
        self.runner = ModelRunner(
            model, mesh=mesh, mp_axis=mp_axis, pp_axis=pp_axis,
            max_batch=max_batch, page_size=page_size,
            prefill_chunk=prefill_chunk, n_pages=self.n_pages,
            use_kernel=use_kernel, kv_cache_dtype=kv_cache_dtype)
        self.pool = PagePool(self.n_pages, prefix_cache=self.prefix_cache,
                             metrics=self._m)
        # host-RAM spill tier (HBM -> host RAM -> recompute hierarchy)
        self.host_spills = 0            # pages demoted device -> host
        self.host_spill_bytes = 0
        self.host_spill_drops = 0       # spill attempts degraded to eviction
        self.host_restores = 0          # pages promoted host -> device
        self.host_restore_bytes = 0
        self.host_restore_failures = 0  # restore attempts fallen to recompute
        self.peer_exports = 0           # pull_pages RPCs served
        self.peer_export_pages = 0
        self.peer_imports = 0           # peer page blocks spliced in
        self.peer_import_pages = 0
        self._tier_retry = RetryPolicy(max_attempts=3, base_delay=0.01,
                                       max_delay=0.25, seed=seed)
        if host_cache_bytes is not None:
            if not self.prefix_cache:
                raise ValueError("host_cache_bytes requires prefix_cache "
                                 "(spilled pages are keyed by chain hash)")
            self.pool.attach_host(HostPageStore(int(host_cache_bytes)),
                                  self.runner.kv_bytes_per_page())
            self.pool.spill_page = self._spill_page
        self.sched = Scheduler(
            self.pool, max_batch=max_batch, max_len=max_len,
            page_size=page_size, pages_per_slot=self.pages_per_slot,
            prefix_cache=self.prefix_cache, copy_page=self.runner.copy_page,
            metrics=self._m, max_waiting=max_waiting,
            shed_min_free_ratio=shed_min_free_ratio,
            restore_chain=self._restore_chain)
        self.prefill_dispatches = 0        # total prefill programs run
        self._next_rid = 0
        self._seed_counter = np.int64(seed) * 1_000_003
        self._auto_block = decode_block == "auto"
        if self._auto_block:
            self.decode_block = max(1, int(decode_block_max))
            self._block_target = 1          # sample k=1 first, then k=2
            self._block_samples: dict = {}  # k -> recent wall dts
            self._block_n = 0               # total samples recorded
        else:
            self.decode_block = max(1, int(decode_block))
        # speculative decoding (off unless spec_decode is a SpecConfig)
        self._spec = spec_decode
        if self._spec is not None:
            self._proposer = self._spec.make_proposer()
        self._spec_samples: dict = {}   # verify rows -> recent wall dts
        self._spec_accept_ema = None    # EMA of per-step acceptance ratio
        self.spec_proposed = 0          # draft tokens sent to verification
        self.spec_accepted = 0          # draft tokens that matched
        self.spec_emitted = 0           # tokens emitted by verify steps
        self.spec_dispatches = 0        # verify programs dispatched
        # fault tolerance: admission control, deadlines, failure isolation
        self.default_deadline = default_deadline
        self.debug_refcount_audit = bool(debug_refcount_audit)
        self._step_retry = (step_retry if step_retry is not None else
                            RetryPolicy(max_attempts=3, base_delay=0.01,
                                        max_delay=0.25, seed=seed))
        self._any_deadline = default_deadline is not None
        self._step_phase = ("admit", ())
        self.step_failures = 0          # step dispatches that raised
        self.step_retries = 0           # transient-path retry invocations
        self.quarantine_probes = 0      # single-slot isolation probes run
        self.resume_admissions = 0      # requests admitted with resume_tokens
        # disaggregation seam: when set, a request whose prompt just
        # finished prefilling is handed to the sink (which detaches it for
        # KV handoff) instead of decoding here — see disagg.DisaggEngine
        self.prefill_sink = None

    # ------------------------------------------------------------- scheduling
    def add_request(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
                    do_sample=False, temperature=1.0, top_p=1.0, top_k=0,
                    seed=None, deadline=None, resume_tokens=None):
        """Submit a request; returns its rid.  ``deadline`` (seconds,
        default ``default_deadline``) bounds its total wall time.  Admission
        control may refuse it: the rid is still returned, but the request is
        already terminal with :attr:`RequestStatus.SHED` (check
        :meth:`status`) — malformed arguments still raise.

        ``resume_tokens``: output history already emitted by a previous
        incarnation of this request (the durable-resume path after a replica
        death).  The history counts as prefill context — it folds into the
        prompt exactly like preemption folds ``prompt0 + out``, so the first
        token generated here continues the sequence and the stream accessors
        emit only NEW tokens; ``max_new_tokens`` is the REMAINING budget.
        Warm prefix-cache pages make the re-prefill cheap.  Token-exactness
        of the continuation: greedy sampling depends only on the context,
        and a fixed ``seed`` keys the sampler identically at every position
        (the generate-parity scheme), so the token at each position is a
        pure function of (seed, context) — identical whether or not the
        request was interrupted.  Seedless ``do_sample`` draws from the
        engine's global counter and promises no cross-replica determinism."""
        n_prompt = int(np.asarray(prompt_ids).reshape(-1).shape[0])
        if n_prompt == 0:
            raise ValueError("empty prompt")
        n_prompt += len(resume_tokens) if resume_tokens is not None else 0
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if n_prompt + int(max_new_tokens) > self.max_len:
            # admitting would silently truncate at max_len (ADVICE r3): the
            # caller must choose — raise max_len or shrink the request
            raise ValueError(
                f"prompt ({n_prompt}) + max_new_tokens ({max_new_tokens}) "
                f"> engine max_len ({self.max_len})")
        vocab = self.cfg.vocab_size
        if int(top_k) > min(_MAXK, vocab):
            raise ValueError(
                f"top_k={top_k} exceeds the engine's in-graph cap "
                f"{min(_MAXK, vocab)} (static top-k window)")
        if deadline is None:
            deadline = self.default_deadline
        r = Request(self._next_rid, prompt_ids, max_new_tokens, eos_token_id,
                    do_sample=do_sample, temperature=temperature,
                    top_p=top_p, top_k=top_k, seed=seed, deadline=deadline,
                    resume_tokens=resume_tokens)
        self._next_rid += 1
        ctx = _flight.current()
        if ctx is not None:
            # adopt the ambient trace (gateway-minted, or RPC-delivered by
            # the worker's server thread) so every scheduler phase records
            r.trace_id = ctx.trace_id
            _flight.record("queued", rid=r.rid, trace_id=r.trace_id,
                           prompt_tokens=len(r.prompt),
                           max_new=r.max_new, resumed=bool(r.resumed_from))
        if r.resumed_from:
            self.resume_admissions += 1
        if deadline is not None:
            self._any_deadline = True
        if self.sched.should_shed():
            self.sched.finalize(r, RequestStatus.SHED)
        else:
            self.sched.waiting.append(r)
        return r.rid

    def cancel(self, rid):
        """Cancel a request wherever it is: waiting (dequeued) or mid-serve
        (slot released — pages return through the refcount machinery, so
        prefix-cache pages other slots share stay live).  Returns True if
        the request was found live; False if unknown or already terminal."""
        return self.sched.cancel(rid)

    def _next_seed(self, r):
        if r.seed is not None:
            return int(r.seed)       # fixed seed: matches model.generate
        self._seed_counter += 1
        return int(self._seed_counter % (2 ** 31 - 1))

    def _prefill_chunk(self, slot):
        sched = self.sched
        r = sched.slots[slot]
        self._step_phase = ("prefill", (slot,))
        _faults.maybe_fire("serving.step", rids=[r.rid], phase="prefill")
        start = r.pos
        n = min(self.chunk, len(r.prompt) - start)
        if self.prefix_cache:
            # about to write [start, start+n): un-share any page another
            # slot still maps (a fully-cached prompt re-prefilling its
            # final token into the last shared page lands here)
            sched.cow_unshare(slot, start, n)
        toks = np.zeros((self.chunk,), np.int32)
        toks[:n] = r.prompt[start:start + n]
        finishes = (start + n) == len(r.prompt)
        r.prefill_dispatches += 1
        self.prefill_dispatches += 1
        self._m.prefill.inc()
        t0 = time.perf_counter()
        with _obs.trace_span("serving.prefill"):
            nxt = self.runner.run_prefill(
                toks, start, sched.slot_tables[slot], n,
                0 if r.do_sample else 1, r.temperature, r.top_p, r.top_k,
                self._next_seed(r))
        if r.trace_id is not None:
            _flight.record("prefill", rid=r.rid, trace_id=r.trace_id,
                           dur=time.perf_counter() - t0, tokens=n,
                           start=start)
        r.pos += n
        sched.lens[slot] = start + n
        if self.prefix_cache:
            sched.register_pages(slot, r)
        if finishes:
            token = int(np.asarray(nxt))
            if self.prefill_sink is not None:
                self.prefill_sink(slot, token)
            else:
                sched.emit(slot, token)

    def step(self):
        """One engine dispatch: a prefill chunk if any slot is mid-prompt,
        else one decode token for every active slot. Returns #slots served.

        This is the failure-isolation boundary: a step that raises never
        kills the engine.  Transient errors (``err.transient`` truthy) are
        retried with backoff; anything else triggers a quarantine sweep —
        the failing dispatch is re-run one slot at a time and the slot that
        still fails alone is finalized FAILED (pages freed), the rest keep
        serving.  Isolation is exact for host-side failures; a fault inside
        an already-dispatched XLA program is best-effort (the donated cache
        buffer may be unrecoverable) — the engine still degrades per-request
        instead of crashing the loop."""
        if self._any_deadline:
            self.sched.expire_deadlines()
        self._step_phase = ("admit", ())
        try:
            served = self._step_impl()
        except Exception as e:  # noqa: BLE001 — the isolation boundary
            served = self._survive_step_failure(e)
        if self.debug_refcount_audit:
            problems = self.audit_refcounts()
            if problems:
                raise RuntimeError("page-refcount audit failed:\n  "
                                   + "\n  ".join(problems))
        return served

    def _step_impl(self):
        sched = self.sched
        sched.admit()
        if _obs.enabled():
            self._refresh_gauges()
        if _faults.active:
            point = _faults.fire("serving.slow_step")
            if point is not None and point.delay:
                time.sleep(point.delay)
        for slot, r in enumerate(sched.slots):
            if r is not None and r.pos < len(r.prompt):
                self._prefill_chunk(slot)
                return 1
        live = [(s, r) for s, r in enumerate(sched.slots) if r is not None]
        if not live:
            return 0
        if self._spec is not None:
            props = self._propose_drafts(live)
            if any(props.values()):
                return self._spec_step(live, props)
            # no slot has a draft this step: the plain decode block below
            # amortizes dispatch cost better than a 1-row verify would
        # block size: largest power of two <= every slot's remaining budget,
        # capped by decode_block (or the RTT-adapted target in auto mode);
        # any eos request needs per-token host inspection -> 1
        cap = self._block_target if self._auto_block else self.decode_block
        k = min(cap, min(r.max_new - len(r.out) for _, r in live))
        if any(r.eos is not None for _, r in live):
            k = 1
        k = 1 << max(0, k.bit_length() - 1)              # floor to pow2
        active = np.zeros((self.max_batch,), np.int32)
        tokens = np.zeros((self.max_batch,), np.int32)
        greedy = np.ones((self.max_batch,), np.int32)
        temp = np.ones((self.max_batch,), np.float32)
        topp = np.ones((self.max_batch,), np.float32)
        topk = np.zeros((self.max_batch,), np.int32)
        seeds = np.zeros((self.max_batch,), np.int32)
        fold = np.zeros((self.max_batch,), np.int32)
        for slot, r in live:
            if sched.slots[slot] is not r:
                continue        # preempted by an earlier slot's growth
            sched.ensure_page(slot, ahead=k)
        # growth may have preempted members of `live` — drop them before
        # building the batch (a stale entry would re-allocate pages to an
        # empty slot and decode a request that is back in the queue)
        live = [(s, r) for s, r in live if sched.slots[s] is r]
        if not live:
            return 0
        for slot, r in live:
            active[slot] = 1
            tokens[slot] = r.out[-1]
            greedy[slot] = 0 if r.do_sample else 1
            temp[slot] = r.temperature
            topp[slot] = r.top_p
            topk[slot] = r.top_k
            seeds[slot] = self._next_seed(r)
            fold[slot] = 1 if r.seed is None else 0
        self._step_phase = ("decode", tuple(s for s, _ in live))
        _faults.maybe_fire("serving.step", rids=[r.rid for _, r in live],
                           phase="decode")
        compile_call = not self.runner.has_decode_program(k)
        self._m.decode.inc()
        t0 = time.perf_counter()
        with _obs.trace_span("serving.decode"):
            toks = self.runner.run_decode(
                k, tokens, sched.lens, sched.slot_tables, active,
                greedy, temp, topp, topk, seeds, fold)       # [k, B]
        dt = time.perf_counter() - t0
        if _flight.enabled():
            for slot, r in live:
                if r.trace_id is not None:
                    _flight.record("decode", rid=r.rid, trace_id=r.trace_id,
                                   dur=dt, block=k)
        if self._auto_block and not compile_call:
            # host sync above makes the wall time a true dispatch sample
            self._record_block_sample(k, dt)
        if not compile_call and _obs.enabled():
            # dispatch served k tokens for each live slot; exclude the
            # compile call so the histogram reflects steady-state latency
            for _ in live:
                self._m.token_latency.observe(dt / k)
        for j in range(k):
            for slot, r in live:
                if sched.slots[slot] is not r:               # released mid-block
                    continue
                sched.lens[slot] += 1
                sched.emit(slot, int(toks[j, slot]))
        return len(live)

    # ----------------------------------------------------- failure isolation
    def _survive_step_failure(self, e):
        """Handle an exception that escaped :meth:`_step_impl`.  Transient
        errors re-dispatch through the shared backoff policy; everything
        else is attributed to a request and quarantined.  Returns the #slots
        the recovery path ended up serving."""
        phase, slots = self._step_phase
        if phase == "admit":
            # failed outside any dispatch — host-side bookkeeping, an
            # engine bug rather than a poison request: surface it
            raise e
        self.step_failures += 1
        self._m.step_fail[phase].inc()
        if getattr(e, "transient", False):
            ok, served, e = self._retry_step()
            if ok:
                return served
            phase, slots = self._step_phase   # the failing retry's phase
            if phase == "admit":
                raise e
        return self._isolate(phase, slots, e)

    def _retry_step(self):
        """Re-dispatch through the shared backoff policy.  Returns ``(True,
        served, None)`` when a retry lands, ``(False, 0, err)`` when the
        attempts run out — or a NON-transient error interrupts the retry
        run; either way isolation takes over from whatever phase the final
        error left in ``_step_phase``."""
        def attempt():
            try:
                return self._step_impl()
            except Exception as err:
                if getattr(err, "transient", False):
                    raise _TransientStep(err) from err
                raise

        def note(n, err, delay):
            self.step_retries += 1

        self.step_retries += 1        # the re-dispatch itself is a retry
        try:
            served = retry_call(attempt, policy=self._step_retry,
                                retry_on=(_TransientStep,),
                                op="serving.step", on_retry=note)
        except RetryError as err:
            return False, 0, err.__cause__.err
        except Exception as err:  # noqa: BLE001 — non-transient mid-retry
            return False, 0, err
        return True, served, None

    def _isolate(self, phase, slots, e):
        """Quarantine the poison request(s) behind a failed dispatch: a
        single-slot failure (prefill, or a 1-wide batch) is attributed
        directly; a batched decode/verify failure is bisected by re-running
        every member slot as a one-slot decode probe and quarantining
        exactly those that still fail alone."""
        todo = [s for s in slots if self.sched.slots[s] is not None]
        if len(todo) <= 1:
            for s in todo:
                self._quarantine(s, e)
            return 0
        served = 0
        for s in todo:
            if self.sched.slots[s] is None:
                continue          # released/preempted by an earlier probe
            self.quarantine_probes += 1
            self._m.probes.inc()
            try:
                self._decode_probe(s)
                served += 1
            except Exception as pe:  # noqa: BLE001 — probe attributes blame
                self._quarantine(s, pe)
        return served

    def _quarantine(self, slot, err):
        """Finalize the slot's request FAILED — the error is recorded on the
        request, its pages return through the refcounts (shared prefix-cache
        pages other slots map stay live) — and keep serving everyone else.
        The victim's trace is pinned in the flight recorder (and dumped when
        a dump dir is configured) so the post-mortem survives ring churn."""
        r = self.sched.slots[slot]
        if r is not None and r.trace_id is not None:
            _flight.pin(r.trace_id, "quarantine")
        self.sched.release(slot, RequestStatus.FAILED, error=err)

    def _decode_probe(self, slot):
        """One-slot k=1 decode dispatch — the isolation probe run for each
        member of a failed batch.  A raise here pins the failure on this
        slot; success emits the token the probe decoded anyway, so a
        surviving request loses no work to the sweep."""
        sched = self.sched
        r = sched.slots[slot]
        self._step_phase = ("decode", (slot,))
        _faults.maybe_fire("serving.step", rids=[r.rid], phase="decode")
        sched.ensure_page(slot, ahead=1)
        if sched.slots[slot] is not r:
            return                # growth preempted the probe target
        active = np.zeros((self.max_batch,), np.int32)
        tokens = np.zeros((self.max_batch,), np.int32)
        greedy = np.ones((self.max_batch,), np.int32)
        temp = np.ones((self.max_batch,), np.float32)
        topp = np.ones((self.max_batch,), np.float32)
        topk = np.zeros((self.max_batch,), np.int32)
        seeds = np.zeros((self.max_batch,), np.int32)
        fold = np.zeros((self.max_batch,), np.int32)
        active[slot] = 1
        tokens[slot] = r.out[-1]
        greedy[slot] = 0 if r.do_sample else 1
        temp[slot] = r.temperature
        topp[slot] = r.top_p
        topk[slot] = r.top_k
        seeds[slot] = self._next_seed(r)
        fold[slot] = 1 if r.seed is None else 0
        self._m.decode.inc()
        with _obs.trace_span("serving.decode_probe"):
            toks = self.runner.run_decode(
                1, tokens, sched.lens, sched.slot_tables, active,
                greedy, temp, topp, topk, seeds, fold)
        sched.lens[slot] += 1
        sched.emit(slot, int(toks[0, slot]))

    def audit_refcounts(self):
        """Cross-check every page-accounting structure against the others;
        returns a list of problem strings (empty means clean).  Invariants:
        each page's refcount equals its slot-table references; free and
        LRU-parked pages carry refcount 0 and never overlap; no page leaks
        (refcount 0 yet neither free nor parked); LRU pages are
        content-registered; the prefix key index is symmetric.  O(pages +
        slots·pages_per_slot); runs after every step under
        ``debug_refcount_audit``."""
        return self.pool.audit(self.sched.expected_refs(self.n_pages))

    def _record_block_sample(self, k, wall_dt):
        """Auto decode-block: least-squares fit of t(k) = RTT + k*c over
        the per-size medians of EVERY sampled block size, targeting the
        power-of-two k where per-dispatch constant costs <= ~25% of device
        time (k >= 3*RTT/c). Fitting all sizes (instead of the two
        earliest medians) lets late samples at large k keep correcting the
        model, and every 64th sample the target drops back to a small k
        for one dispatch so the intercept estimate can't go stale."""
        samples = self._block_samples.setdefault(k, [])
        samples.append(wall_dt)
        del samples[:-8]
        self._block_n += 1
        sampled = {kk: sorted(v)[len(v) // 2]
                   for kk, v in self._block_samples.items() if v}
        if len(sampled) < 2:
            # force a second sample size next step so the model is solvable
            self._block_target = min(2, self.decode_block) \
                if 1 in sampled else 1
            return
        ks = sorted(sampled)
        c, rtt = np.polyfit(np.asarray(ks, np.float64),
                            np.asarray([sampled[kk] for kk in ks],
                                       np.float64), 1)
        if c <= 0 or rtt <= 0:       # noise/local runtime: RTT negligible
            self._block_target = min(2, self.decode_block)
            return
        want = max(1, int(3 * rtt / c))
        want = 1 << (want.bit_length() - 1)              # floor to pow2
        self._block_target = min(want, self.decode_block)
        if self._block_n % 64 == 0:
            # periodic small-k re-sample refreshes the RTT intercept
            self._block_target = min(2, self.decode_block)

    @property
    def auto_decode_block(self):
        """Current RTT-adapted block target (auto mode only)."""
        return self._block_target if self._auto_block else self.decode_block

    def run_until_done(self, max_steps=10000):
        steps = 0
        while (self.sched.waiting
               or any(s is not None for s in self.sched.slots)) \
                and steps < max_steps:
            self.step()
            steps += 1
        return steps

    def _refresh_gauges(self):
        """Mirror instantaneous engine state into the registry gauges."""
        n_active = sum(1 for s in self.sched.slots if s is not None)
        self._m.queue_depth.set(len(self.sched.waiting))
        self._m.active_slots.set(n_active)
        self._m.occupancy.set(n_active / self.max_batch)
        self._m.cached_pages.set(len(self.pool.key_page))
        self._m.reclaimable.set(len(self.pool.lru))
        self._m.free_pages.set(len(self.pool.free_pages))
        if self.pool.host is not None:
            self._m.host_cached.set(len(self.pool.host))

    def metrics(self):
        """This engine's telemetry series from the process-wide registry.

        Values accumulate only while ``paddle_tpu.observability.enable()``
        is on; :meth:`prefix_cache_stats` stays the always-on plain-dict
        view of the same counters."""
        if _obs.enabled():
            self._refresh_gauges()
        return _obs.snapshot(prefix="serving_",
                             labels={"engine": self._m.label})

    def prefix_cache_stats(self):
        """Counters for the automatic prefix cache (all zero when the
        `prefix_cache` knob is off).

        The same counters are exported through the observability registry
        (``serving_prefix_cache_events_total{engine=...}``); this dict is
        the always-on thin compatibility view."""
        return {
            "hits": self.pool.cache_hits,
            "misses": self.pool.cache_misses,
            "evictions": self.pool.cache_evictions,
            "cow_copies": self.pool.cache_cow_copies,
            "prefill_dispatches": self.prefill_dispatches,
            "cached_pages": len(self.pool.key_page),
            "reclaimable_pages": len(self.pool.lru),
        }

    def kv_bytes_per_page(self):
        """HBM bytes one KV page costs across all layers (both K and V,
        including int8 scales) — the unit of the page_pool budget."""
        return self.runner.kv_bytes_per_page()

    # ---------------------------------------------------------- KV tiering
    def _spill_page(self, p):
        """Device half of a host-tier spill: gather page ``p``'s contents
        into host RAM (injected into the pool as ``spill_page``).  The
        ``kv.spill`` fault point sits in front of the copy: transient
        firings retry through the tier backoff policy; a poison firing (or
        exhausted retries) returns None and the page degrades to a plain
        eviction — recompute on the next hit, never corruption."""
        def attempt():
            try:
                _faults.maybe_fire("kv.spill", page=int(p))
                return self.runner.pages_to_host([int(p)])
            except Exception as err:
                if getattr(err, "transient", False):
                    raise _TransientTier(err) from err
                raise

        try:
            blk = retry_call(attempt, policy=self._tier_retry,
                             retry_on=(_TransientTier,), op="kv.spill")
        except Exception:  # noqa: BLE001 — lossless fallback: eviction
            self.host_spill_drops += 1
            return None
        nbytes = sum(int(a.nbytes) for a in blk)
        self.host_spills += 1
        self.host_spill_bytes += nbytes
        self._m.tier_spills.inc()
        self._m.tier_spill_bytes.inc(nbytes)
        return blk

    def _restore_chain(self, keys):
        """Host half of a spill restore (injected into the scheduler as
        ``restore_chain``): bring the host-tier blocks for chain ``keys``
        back into freshly-allocated device pages via double-buffered
        host→device prefetch, and re-register them in the prefix index.
        Returns the restored physical pages IN ORDER, referenced once each
        for the caller's slot table — possibly shorter than ``keys`` (an
        aged-out entry, a dry pool, or a poison ``kv.restore`` firing);
        admission truncates its cached prefix there and the tail
        re-prefills (recompute fallback)."""
        host = self.pool.host
        if host is None:
            return []

        def attempt():
            try:
                _faults.maybe_fire("kv.restore", keys=list(keys))
            except Exception as err:
                if getattr(err, "transient", False):
                    raise _TransientTier(err) from err
                raise

        try:
            retry_call(attempt, policy=self._tier_retry,
                       retry_on=(_TransientTier,), op="kv.restore")
        except Exception:  # noqa: BLE001 — lossless fallback: recompute
            self.host_restore_failures += 1
            return []
        blocks, pages = [], []
        try:
            for key in keys:
                blk = host.get(key)
                if blk is None:
                    break
                p = self.pool.alloc_page()
                if p is None:
                    break
                blocks.append(blk)
                pages.append(p)
            if not pages:
                return []
            self.runner.restore_pages(pages, blocks)
        except Exception:  # noqa: BLE001 — unwritten pages free cleanly
            for p in pages:
                self.pool.unref_page(p)
            self.host_restore_failures += 1
            return []
        for p, key in zip(pages, keys):
            self.pool.register(p, key)
        nbytes = sum(HostPageStore.block_bytes(b) for b in blocks)
        self.host_restores += len(pages)
        self.host_restore_bytes += nbytes
        self._m.tier_restores.inc(len(pages))
        self._m.tier_restore_bytes.inc(nbytes)
        return pages

    def export_pages(self, keys):
        """Serve a peer replica's ``pull_pages`` RPC: the longest prefix of
        chain ``keys`` this engine holds in ANY tier, as one dense host
        block (HBM pages gathered in a single dispatch, host-tier entries
        read in place).  Returns ``{"keys": [...], "block": tuple of
        [L, n, page, ...] numpy arrays}``, or None when even the first key
        misses everywhere — the puller then recomputes."""
        host = self.pool.host
        served, dev, host_blocks = [], [], {}
        for i, key in enumerate(keys):
            p = self.pool.lookup(key)
            if p is not None:
                dev.append((i, int(p)))
            else:
                blk = host.get(key) if host is not None else None
                if blk is None:
                    break
                host_blocks[i] = blk
            served.append(key)
        if not served:
            return None
        dev_blk = self.runner.pages_to_host([p for _, p in dev]) \
            if dev else None
        parts = [None] * len(served)
        for j, (i, _) in enumerate(dev):
            parts[i] = tuple(a[:, j:j + 1] for a in dev_blk)
        for i, blk in host_blocks.items():
            parts[i] = blk
        n_comp = len(parts[0])
        block = tuple(np.concatenate([pk[c] for pk in parts], axis=1)
                      if len(parts) > 1 else np.ascontiguousarray(parts[0][c])
                      for c in range(n_comp))
        self.peer_exports += 1
        self.peer_export_pages += len(served)
        self._m.tier_peer_export.inc(len(served))
        self._m.tier_peer_bytes_out.inc(sum(int(a.nbytes) for a in block))
        return {"keys": served, "block": block}

    def import_pages(self, payload):
        """Splice a peer's exported page block into this engine's pool and
        prefix index (the receive half of a peer pull).  Keys already
        resident in either tier are skipped; each spliced page is
        content-registered then immediately unreferenced into the LRU
        (cached, refcount 0), so the next admission walk claims it as an
        ordinary prefix hit.  Any failure stops the splice mid-chain — the
        un-spliced tail simply recomputes.  Returns pages spliced."""
        if not payload:
            return 0
        keys, block = payload["keys"], payload["block"]
        host = self.pool.host
        n = 0
        for i, key in enumerate(keys):
            if self.pool.lookup(key) is not None \
                    or (host is not None and key in host):
                continue
            # slice the peer block BEFORE allocating: a malformed payload
            # raising here must not strand a referenced page
            blk = tuple(np.ascontiguousarray(a[:, i:i + 1]) for a in block)
            p = self.pool.alloc_page()
            if p is None:
                break
            try:
                self.runner.restore_pages([p], [blk])
            except Exception:  # noqa: BLE001 — lossless: recompute the tail
                self.pool.unref_page(p)
                break
            self.pool.register(p, key)
            self.pool.unref_page(p)      # cached, refcount 0 -> LRU parked
            n += 1
        if n:
            self.peer_imports += 1
            self.peer_import_pages += n
            self._m.tier_peer_import.inc(n)
            self._m.tier_peer_bytes_in.inc(
                sum(int(a.nbytes) for a in block) * n // max(1, len(keys)))
        return n

    def kv_tier_stats(self):
        """Counters for the KV-cache hierarchy (HBM → host RAM → peer →
        recompute); all zero when no tier knob is on.  The same counters
        are exported through the registry (``serving_kv_tier_*``)."""
        host = self.pool.host
        return {
            "host_spills": self.host_spills,
            "host_spill_bytes": self.host_spill_bytes,
            "host_spill_drops": self.host_spill_drops,
            "host_restores": self.host_restores,
            "host_restore_bytes": self.host_restore_bytes,
            "host_restore_failures": self.host_restore_failures,
            "host_cached_pages": len(host) if host is not None else 0,
            "host_bytes": host.bytes_used if host is not None else 0,
            "host_evictions": host.evictions if host is not None else 0,
            "hits_hbm": self.pool.cache_hits - self.pool.host_hits,
            "hits_host": self.pool.host_hits,
            "peer_exports": self.peer_exports,
            "peer_export_pages": self.peer_export_pages,
            "peer_imports": self.peer_imports,
            "peer_import_pages": self.peer_import_pages,
        }

    def prefix_keys(self):
        """Chain keys currently resident in the prefix cache — HBM pages
        AND host-tier spilled chains (empty when the ``prefix_cache`` knob
        is off).  The multi-process fleet snapshots this over RPC to keep
        the gateway's prefix-affinity router warm for replicas whose cache
        events it cannot observe in-process; advertising spilled chains
        lets the router score (and peers pull) prefixes this replica can
        restore without recompute."""
        keys = list(self.pool.key_page)
        if self.pool.host is not None:
            resident = self.pool.key_page
            keys.extend(k for k in self.pool.host.keys()
                        if k not in resident)
        return keys

    def result(self, rid):
        return self.sched.finished[rid].out

    def ttft(self, rid):
        """Seconds from add_request to the first generated token."""
        return self.sched.finished[rid].ttft

    def tpot(self, rid):
        """Mean seconds per output token AFTER the first (the TPOT the
        decode phase is responsible for); None while the request has not
        finished or emitted fewer than two tokens."""
        r = self._lookup(rid)
        if r.t_finish is None or r.ttft is None or len(r.out) < 2:
            return None
        return (r.t_finish - r.t_submit - r.ttft) / (len(r.out) - 1)

    def _lookup(self, rid):
        """The live or terminal :class:`Request` for ``rid`` wherever it
        is — waiting, in a slot, or finished.  KeyError when unknown."""
        return self.sched.lookup(rid)

    def new_tokens(self, rid):
        """Incremental stream accessor: the tokens ``rid`` generated since
        the previous ``new_tokens(rid)`` call (empty list when none yet).
        Output is append-only across the whole lifecycle — preemption
        re-folds the *prompt*, never the emitted stream — so concatenating
        every batch reproduces :meth:`result` exactly.  This is the public
        surface the streaming gateway reads; it never touches slot state."""
        r = self._lookup(rid)
        toks = [int(t) for t in r.out[r.stream_pos:]]
        r.stream_pos += len(toks)
        return toks

    def stream(self, rid, max_steps=100000):
        """Generator driving the engine until ``rid`` is terminal, yielding
        its tokens one by one as they are emitted (other in-flight requests
        keep being served by the same steps).  Single-caller convenience —
        a multi-replica front door runs the step loop elsewhere and polls
        :meth:`new_tokens` instead."""
        steps = 0
        while True:
            yield from self.new_tokens(rid)
            if self._lookup(rid).status.terminal:
                return
            if steps >= max_steps:
                raise RuntimeError(f"stream({rid}) exceeded {max_steps} steps")
            self.step()
            steps += 1

    def fail_all(self, error):
        """Finalize EVERY live request (waiting and running) as FAILED with
        ``error`` recorded — the front door calls this when a replica's
        step loop dies, so inflight requests end with a typed terminal
        status instead of hanging their streams forever."""
        self.sched.fail_all(error)

    def status(self, rid):
        """The request's :class:`RequestStatus` wherever it lives — waiting,
        in a slot, or terminal.  KeyError for an unknown rid."""
        return self._lookup(rid).status

    def error(self, rid):
        """The recorded ``ExceptionType: message`` string for a FAILED
        request; None for every other terminal status."""
        return self.sched.finished[rid].error

    def health(self):
        """One JSON-able liveness snapshot for external monitors — plain
        counters, available whether or not observability is enabled."""
        n_active = sum(1 for s in self.sched.slots if s is not None)
        return {
            "active_slots": n_active,
            "max_batch": self.max_batch,
            "waiting": len(self.sched.waiting),
            "finished": len(self.sched.finished),
            "free_pages": len(self.pool.free_pages),
            "reclaimable_pages": len(self.pool.lru),
            "total_pages": self.n_pages - 1,
            "host_cached_pages": (len(self.pool.host)
                                  if self.pool.host is not None else 0),
            "host_headroom_pages": self.pool.host_headroom_pages(),
            "host_bytes": (self.pool.host.bytes_used
                           if self.pool.host is not None else 0),
            "shed_requests": self.sched.shed_requests,
            "timeouts": self.sched.timeouts,
            "cancels": self.sched.cancels,
            "quarantined": self.sched.quarantined,
            "step_failures": self.step_failures,
            "step_retries": self.step_retries,
            "quarantine_probes": self.quarantine_probes,
            "resume_admissions": self.resume_admissions,
            "preemptions": self.sched.preemptions,
        }
