"""Per-engine registry bindings (label ``engine=<seq>``).

One :class:`_EngineMetrics` is built per :class:`~.core.LLMEngine`; every
series the engine touches on the hot path is resolved to a labelled child
exactly once here, so the step loop never pays a registry lookup.
"""
from __future__ import annotations

from ... import observability as _obs
from .request import TERMINAL_STATUSES

__all__ = ["_EngineMetrics", "_PoolMetrics"]


class _EngineMetrics:
    """Registry children bound once per engine (label ``engine=<seq>``).

    Every mutation is a no-op while observability is disabled, so the engine
    attributes (cache_hits, preemptions, ...) stay the always-on source of
    truth and the registry mirrors them 1:1 whenever metrics are on — the
    parity :meth:`LLMEngine.prefix_cache_stats` keeps by construction."""

    def __init__(self, label):
        e = {"engine": label}
        self.label = label
        self.ttft = _obs.SERVING_TTFT.labels(**e)
        self.token_latency = _obs.SERVING_TOKEN_LATENCY.labels(**e)
        self.queue_depth = _obs.SERVING_QUEUE_DEPTH.labels(**e)
        self.active_slots = _obs.SERVING_ACTIVE_SLOTS.labels(**e)
        self.occupancy = _obs.SERVING_OCCUPANCY.labels(**e)
        self.prefill = _obs.SERVING_DISPATCHES.labels(kind="prefill", **e)
        self.decode = _obs.SERVING_DISPATCHES.labels(kind="decode", **e)
        self.tokens = _obs.SERVING_TOKENS.labels(**e)
        self.preempt = _obs.SERVING_PREEMPTIONS.labels(**e)
        self.hits = _obs.SERVING_CACHE_EVENTS.labels(event="hit", **e)
        self.misses = _obs.SERVING_CACHE_EVENTS.labels(event="miss", **e)
        self.evictions = _obs.SERVING_CACHE_EVENTS.labels(event="eviction",
                                                          **e)
        self.cow = _obs.SERVING_CACHE_EVENTS.labels(event="cow_copy", **e)
        self.cached_pages = _obs.SERVING_CACHED_PAGES.labels(**e)
        self.reclaimable = _obs.SERVING_RECLAIMABLE_PAGES.labels(**e)
        self.free_pages = _obs.SERVING_FREE_PAGES.labels(**e)
        # KV-cache hierarchy (host spill tier + peer pulls)
        self.tier_spills = _obs.SERVING_KV_TIER_EVENTS.labels(
            event="spill", **e)
        self.tier_restores = _obs.SERVING_KV_TIER_EVENTS.labels(
            event="restore", **e)
        self.tier_peer_export = _obs.SERVING_KV_TIER_EVENTS.labels(
            event="peer_export", **e)
        self.tier_peer_import = _obs.SERVING_KV_TIER_EVENTS.labels(
            event="peer_import", **e)
        self.tier_spill_bytes = _obs.SERVING_KV_TIER_BYTES.labels(
            direction="spill", **e)
        self.tier_restore_bytes = _obs.SERVING_KV_TIER_BYTES.labels(
            direction="restore", **e)
        self.tier_peer_bytes_out = _obs.SERVING_KV_TIER_BYTES.labels(
            direction="peer_out", **e)
        self.tier_peer_bytes_in = _obs.SERVING_KV_TIER_BYTES.labels(
            direction="peer_in", **e)
        self.tier_hits_hbm = _obs.SERVING_KV_TIER_HITS.labels(
            tier="hbm", **e)
        self.tier_hits_host = _obs.SERVING_KV_TIER_HITS.labels(
            tier="host", **e)
        self.host_cached = _obs.SERVING_HOST_CACHED_PAGES.labels(**e)
        self.verify = _obs.SERVING_DISPATCHES.labels(kind="verify", **e)
        self.spec_proposed = _obs.SERVING_SPEC_PROPOSED.labels(**e)
        self.spec_accepted = _obs.SERVING_SPEC_ACCEPTED.labels(**e)
        self.spec_acceptance = _obs.SERVING_SPEC_ACCEPTANCE.labels(**e)
        self.terminal = {s: _obs.SERVING_TERMINALS.labels(status=s.value, **e)
                         for s in TERMINAL_STATUSES}
        self.step_fail = {ph: _obs.SERVING_STEP_FAILURES.labels(phase=ph, **e)
                          for ph in ("prefill", "decode", "verify")}
        self.probes = _obs.SERVING_QUARANTINE_PROBES.labels(**e)


class _PoolMetrics:
    """Registry children bound once per :class:`~.disagg.DisaggEngine`
    (label ``pool=<seq>``) — the handoff seam's queue gauge plus the
    wait/transfer histograms, split by how the block crossed (``local``:
    jitted gather → device_put; ``cross_host``: serialized over the worker
    RPC plane).  ``handoff_stats()`` mirrors the same numbers always-on."""

    def __init__(self, label):
        p = {"pool": label}
        self.label = label
        self.queue_depth = _obs.SERVING_HANDOFF_QUEUE_DEPTH.labels(**p)
        self.wait = {path: _obs.SERVING_HANDOFF_WAIT_SECONDS.labels(
            path=path, **p) for path in ("local", "cross_host")}
        self.transfer = {path: _obs.SERVING_HANDOFF_TRANSFER_SECONDS.labels(
            path=path, **p) for path in ("local", "cross_host")}
