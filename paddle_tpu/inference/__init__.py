"""paddle.inference analog — the deployment predictor surface (reference:
fluid/inference/api/analysis_predictor.h:101 AnalysisPredictor +
paddle_inference_api.h Config/Predictor/Tensor).

TPU-native: the "optimized program" is the jax.export StableHLO artifact
written by paddle.jit.save; Config points at it, create_predictor loads it and
jits execution. Input/output handles copy through numpy (zero-copy within the
process via jax arrays)."""
from __future__ import annotations

import os

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorPool"]


class Config:
    """reference: analysis_config.cc — model path + runtime knobs."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        if model_dir and prog_file is None:
            # accept either a dir containing "inference.pdmodel" or a prefix
            if os.path.isdir(model_dir):
                prefix = os.path.join(model_dir, "inference")
            else:
                prefix = model_dir
        else:
            prefix = (prog_file or "").replace(".pdmodel", "")
        self._prefix = prefix
        self._batch = 1
        self._device = None
        self._memory_pool_mb = 0
        self._enable_profile = False

    def model_path(self):
        return self._prefix

    def enable_xpu(self, *a, **k):
        pass

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._memory_pool_mb = memory_pool_init_size_mb
        self._device = device_id

    def disable_gpu(self):
        self._device = None

    def enable_profile(self):
        self._enable_profile = True

    def set_cpu_math_library_num_threads(self, n):
        pass

    def switch_ir_optim(self, flag=True):
        pass


class _IOHandle:
    """Input/output tensor handle (reference ZeroCopyTensor)."""

    def __init__(self, predictor, idx, is_input):
        self._p = predictor
        self._idx = idx
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        assert self._is_input
        self._p._inputs[self._idx] = np.asarray(arr)

    def reshape(self, shape):
        pass

    def copy_to_cpu(self):
        assert not self._is_input
        return np.asarray(self._p._outputs[self._idx])

    def shape(self):
        src = self._p._inputs if self._is_input else self._p._outputs
        a = src[self._idx]
        return list(a.shape) if a is not None else None


class Predictor:
    """reference AnalysisPredictor: named IO handles + run()."""

    def __init__(self, config: Config):
        from ..jit import load
        self._config = config
        self._layer = load(config.model_path())
        spec = getattr(self._layer, "_input_spec", None)
        n_in = len(spec) if spec else len(self._layer._exported.in_avals) - 1
        self._input_names = [f"x{i}" for i in range(max(n_in, 1))]
        self._inputs = [None] * len(self._input_names)
        self._outputs = []

    # ---- handle surface -----------------------------------------------------
    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return _IOHandle(self, self._input_names.index(name), True)

    def get_output_names(self):
        return [f"out{i}" for i in range(len(self._outputs) or 1)]

    def get_output_handle(self, name):
        idx = int(name.replace("out", "") or 0)
        return _IOHandle(self, idx, False)

    # ---- execution ----------------------------------------------------------
    def run(self, inputs=None):
        """Batch-friendly run: positional list of numpy arrays (or via the
        copy_from_cpu handles). Returns list of numpy outputs."""
        if inputs is not None:
            self._inputs = [np.asarray(i) for i in inputs]
        if any(i is None for i in self._inputs):
            raise RuntimeError("predictor inputs not set")
        out = self._layer(*self._inputs)
        outs = out if isinstance(out, tuple) else (out,)
        self._outputs = [np.asarray(o.numpy()) for o in outs]
        return self._outputs

    def clone(self):
        return Predictor(self._config)

    def clear_intermediate_tensor(self):
        pass


class PredictorPool:
    """reference: paddle_inference_api.h PredictorPool — N cloned predictors."""

    def __init__(self, config: Config, size: int = 1):
        self._predictors = [Predictor(config) for _ in range(size)]

    def retrieve(self, idx):
        return self._predictors[idx]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


# ---- round-2 compat surface (reference paddle/inference/__init__.py) --------
class DataType:
    """reference pybind PaddleDType enum."""
    FLOAT64 = 0
    FLOAT32 = 1
    FLOAT16 = 2
    BFLOAT16 = 3
    INT64 = 4
    INT32 = 5
    INT8 = 6
    UINT8 = 7
    BOOL = 8


class PlaceType:
    kUNK = -1
    kCPU = 0
    kGPU = 1
    kXPU = 2
    kCUSTOM = 3


class PrecisionType:
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


def get_version():
    from ..version import __version__
    return f"paddle_tpu inference {__version__}"


def get_num_bytes_of_data_type(dtype):
    import numpy as np
    sizes = {DataType.FLOAT64: 8, DataType.FLOAT32: 4, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2, DataType.INT64: 8, DataType.INT32: 4,
             DataType.INT8: 1, DataType.UINT8: 1, DataType.BOOL: 1}
    return sizes.get(dtype, 4)


def get_trt_compile_version():
    return (0, 0, 0)     # no TensorRT on the TPU build


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name):
    return op_name       # XLA HLO names are the kernel names here


def convert_to_mixed_precision(*a, **k):
    raise NotImplementedError(
        "convert_to_mixed_precision: export with paddle.jit.save under "
        "amp.auto_cast instead (bf16 is the native serving dtype on TPU)")


class XpuConfig:
    def __init__(self, *a, **k):
        raise NotImplementedError("XPU inference is not part of the TPU build")


from ..core.tensor import Tensor  # noqa: F401,E402  (zero-copy IO handle type)
