"""Serving runtime for (sharded) LLMs — the role the reference fills with
the FleetExecutor actor/interceptor pipeline for multi-stage inference
(paddle/fluid/distributed/fleet_executor/carrier.cc) plus the paged
KV-cache fused ops (phi/kernels/fusion block_multi_head_attention; the
encoder/decoder split there is seq_lens_encoder vs seq_lens_decoder,
python/paddle/incubate/nn/functional/block_multihead_attention.py:33, and
sampling is in-op via phi top_p_sampling).

The implementation lives in :mod:`paddle_tpu.inference.engine` — the
monolith split into ``request`` / ``pages`` / ``runner`` / ``spec`` /
``scheduler`` / ``core`` / ``disagg`` along the scheduler–pool–runner
interfaces (see that package's docstring for the layering).  This module
is the stable import surface: everything historically imported from
``paddle_tpu.inference.serving`` keeps resolving here.

TPU-native design (details in the engine modules):
- TWO jitted programs serve the whole engine: a chunked PREFILL step and a
  token-level continuous-batching DECODE step (Orca-style); sampling is
  in-graph with per-slot parameters, matching ``model.generate``
  token-for-token at equal seed.
- KV lives in PAGES [L, n_pages, page, KVH, D] with host-managed per-slot
  page tables, on-demand growth, and youngest-slot preemption-recompute
  when the pool runs dry (vLLM-style).
- AUTOMATIC PREFIX CACHING (``prefix_cache=True``): chain-hashed full
  prompt pages, refcounted sharing, copy-on-write, LRU reclaim — cached KV
  is bit-identical to recomputation, so hits change dispatch counts, never
  tokens.
- Weights are stacked [L, ...] and placed with NamedShardings (layers over
  pp, head/ffn dims over mp); GSPMD inserts the collectives.
- DISAGGREGATED PREFILL/DECODE (:class:`DisaggEngine`): the two phases on
  separate mesh slices with KV-page handoff, so decode token cadence never
  stalls behind a prompt.
"""
from __future__ import annotations

from .engine import (  # noqa: F401
    DisaggEngine,
    LLMEngine,
    ModelRunner,
    PagePool,
    Request,
    RequestStatus,
    Scheduler,
    SpecConfig,
    prefix_page_keys,
    split_mesh,
)
from .engine.spec import _NgramProposer  # noqa: F401  (test/bench import)

__all__ = ["LLMEngine", "DisaggEngine", "split_mesh", "Request",
           "RequestStatus", "SpecConfig", "prefix_page_keys",
           "Scheduler", "PagePool", "ModelRunner"]
