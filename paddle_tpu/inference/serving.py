"""Serving runtime for (sharded) LLMs — the role the reference fills with
the FleetExecutor actor/interceptor pipeline for multi-stage inference
(paddle/fluid/distributed/fleet_executor/carrier.cc) plus the paged
KV-cache fused ops (phi/kernels/fusion block_multi_head_attention).

TPU-native design:
- ONE jitted token step serves the whole engine. Requests are admitted into
  fixed slots; a slot still consuming its prompt feeds prompt tokens, a slot
  past its prompt feeds its last generated token — token-level continuous
  batching (Orca-style) with no separate prefill program or shape buckets.
- KV lives in PAGES [L, n_pages, page, KVH, D] with host-managed per-slot
  page tables; decode attention runs against the paged cache
  (ops/pallas/paged_attention kernel on a single TPU chip; the partitionable
  jnp formulation under GSPMD meshes, where XLA shards the gathers).
- Weights are extracted from the model once, stacked [L, ...] and placed
  with NamedShardings: layers sharded over the pp axis (stage-partitioned
  memory), head/ffn dims over the mp axis. The step function is pure jax
  over those arrays; GSPMD inserts the collectives.
"""
from __future__ import annotations

import math
from collections import deque

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["LLMEngine", "Request"]


class Request:
    def __init__(self, rid, prompt_ids, max_new_tokens, eos_token_id=None):
        self.rid = rid
        self.prompt = list(int(t) for t in np.asarray(prompt_ids).reshape(-1))
        self.max_new = int(max_new_tokens)
        self.eos = eos_token_id
        self.out: list[int] = []
        self.pos = 0                 # tokens already fed to the engine
        self.slot = None
        self.done = False


def _rope(x, pos, theta):
    """neox-style RoPE at integer positions pos [B] (x [B, Hn, D])."""
    D = x.shape[-1]
    inv = 1.0 / (theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    freqs = pos.astype(jnp.float32)[:, None] * inv[None, :]      # [B, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)               # [B, D]
    s, c = jnp.sin(emb)[:, None, :], jnp.cos(emb)[:, None, :]
    xf = x.astype(jnp.float32)
    half = D // 2
    rot = jnp.concatenate([-xf[..., half:], xf[..., :half]], axis=-1)
    return (xf * c + rot * s).astype(x.dtype)


def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32)).astype(
        x.dtype)


class LLMEngine:
    """Continuous-batching paged-KV engine over a LlamaForCausalLM."""

    def __init__(self, model, mesh=None, mp_axis="mp", pp_axis="pp",
                 max_batch=4, max_len=256, page_size=16, use_kernel=None):
        cfg = model.config
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len
        self.page = page_size
        self.pages_per_slot = math.ceil(max_len / page_size)
        # +1: a trash page absorbing the (masked-out) writes of inactive slots
        self.n_pages = max_batch * self.pages_per_slot + 1
        self.trash_page = self.n_pages - 1
        self.mesh = mesh
        L = cfg.num_hidden_layers
        H = cfg.hidden_size
        nh, kvh = cfg.num_attention_heads, cfg.num_key_value_heads
        D = H // nh
        self.nh, self.kvh, self.D = nh, kvh, D
        if use_kernel is None:
            use_kernel = (mesh is None and
                          jax.devices()[0].platform in ("tpu", "axon"))
        self.use_kernel = use_kernel

        def wb(lin):        # Linear stores weight [in, out]
            return np.asarray(lin.weight._data)

        lay = model.llama.layers
        W = {
            "embed": np.asarray(model.llama.embed_tokens.weight._data),
            "norm": np.asarray(model.llama.norm.weight._data),
            "wq": np.stack([wb(l.self_attn.q_proj) for l in lay]),
            "wk": np.stack([wb(l.self_attn.k_proj) for l in lay]),
            "wv": np.stack([wb(l.self_attn.v_proj) for l in lay]),
            "wo": np.stack([wb(l.self_attn.o_proj) for l in lay]),
            "ln1": np.stack([np.asarray(l.input_layernorm.weight._data)
                             for l in lay]),
            "ln2": np.stack([np.asarray(
                l.post_attention_layernorm.weight._data) for l in lay]),
            "wg": np.stack([wb(l.mlp.gate_proj) for l in lay]),
            "wu": np.stack([wb(l.mlp.up_proj) for l in lay]),
            "wd": np.stack([wb(l.mlp.down_proj) for l in lay]),
        }
        W["head"] = (np.asarray(model.lm_head.weight._data)
                     if model.lm_head is not None else W["embed"].T)
        dtype = W["wq"].dtype
        if mesh is not None:
            pp = pp_axis if pp_axis in mesh.axis_names else None
            mp = mp_axis if mp_axis in mesh.axis_names else None

            def put(name, arr, spec):
                return jax.device_put(jnp.asarray(arr),
                                      NamedSharding(mesh, spec))
            specs = {
                "embed": P(), "norm": P(), "head": P(None, mp),
                "wq": P(pp, None, mp), "wk": P(pp, None, mp),
                "wv": P(pp, None, mp), "wo": P(pp, mp, None),
                "ln1": P(pp, None), "ln2": P(pp, None),
                "wg": P(pp, None, mp), "wu": P(pp, None, mp),
                "wd": P(pp, mp, None),
            }
            self.W = {k: put(k, v, specs[k]) for k, v in W.items()}
            cache_spec = NamedSharding(mesh, P(pp))
        else:
            self.W = {k: jnp.asarray(v) for k, v in W.items()}
            cache_spec = None
        kp = jnp.zeros((L, self.n_pages, page_size, kvh, D), dtype)
        vp = jnp.zeros_like(kp)
        if cache_spec is not None:
            kp = jax.device_put(kp, cache_spec)
            vp = jax.device_put(vp, cache_spec)
        self.kp, self.vp = kp, vp

        # host scheduler state (trash page is never allocated)
        self._free_pages = deque(range(self.n_pages - 1))
        self._slots: list = [None] * max_batch
        self._slot_tables = np.zeros((max_batch, self.pages_per_slot),
                                     np.int32)
        self._lens = np.zeros((max_batch,), np.int32)
        self._waiting: deque = deque()
        self._finished: dict = {}
        self._next_rid = 0
        self._step = self._build_step()

    # ------------------------------------------------------------------ step
    def _build_step(self):
        cfg = self.cfg
        nh, kvh, D = self.nh, self.kvh, self.D
        page = self.page
        eps = cfg.rms_norm_eps
        theta = cfg.rope_theta
        use_kernel = self.use_kernel
        trash = self.trash_page

        def step(W, kp, vp, tokens, lens, tables, active):
            # tokens [B] int32; lens [B] tokens already cached; tables
            # [B, S] page ids; active [B] 0/1
            x = W["embed"][tokens]                       # [B, H]
            pos = lens.astype(jnp.int32)
            page_idx = jnp.take_along_axis(
                tables, (pos // page)[:, None], axis=1)[:, 0]
            # inactive slots write into the trash page, never a live one
            page_idx = jnp.where(active > 0, page_idx, trash)
            within = pos % page
            ctx = jnp.where(active > 0, pos + 1, 1).astype(jnp.int32)

            def layer(carry, wl):
                x, = carry
                h = _rms(x, wl["ln1"], eps)
                q = (h @ wl["wq"]).reshape(-1, nh, D)
                k = (h @ wl["wk"]).reshape(-1, kvh, D)
                v = (h @ wl["wv"]).reshape(-1, kvh, D)
                q = _rope(q, pos, theta)
                k = _rope(k, pos, theta)
                kpl = wl["kp"].at[page_idx, within].set(k)
                vpl = wl["vp"].at[page_idx, within].set(v)
                if use_kernel:
                    from ..ops.pallas.paged_attention import paged_attention
                    att = paged_attention(q, kpl, vpl, tables, ctx)
                else:
                    from ..ops.pallas.paged_attention import \
                        paged_attention_ref
                    att = paged_attention_ref(q, kpl, vpl, tables, ctx)
                x = x + att.reshape(-1, nh * D) @ wl["wo"]
                h = _rms(x, wl["ln2"], eps)
                gate = h @ wl["wg"]
                up = h @ wl["wu"]
                x = x + (jax.nn.silu(gate.astype(jnp.float32)).astype(
                    up.dtype) * up) @ wl["wd"]
                return (x,), (kpl, vpl)

            per_layer = {k: W[k] for k in
                         ("wq", "wk", "wv", "wo", "ln1", "ln2",
                          "wg", "wu", "wd")}
            per_layer["kp"] = kp
            per_layer["vp"] = vp
            (x,), (kp2, vp2) = jax.lax.scan(layer, (x,), per_layer)
            h = _rms(x, W["norm"], eps)
            logits = h.astype(jnp.float32) @ W["head"].astype(jnp.float32)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return nxt, kp2, vp2

        return jax.jit(step, donate_argnums=(1, 2))

    # ------------------------------------------------------------- scheduling
    def add_request(self, prompt_ids, max_new_tokens=16, eos_token_id=None):
        n_prompt = int(np.asarray(prompt_ids).reshape(-1).shape[0])
        if n_prompt >= self.max_len:
            raise ValueError(
                f"prompt length {n_prompt} >= engine max_len {self.max_len}; "
                "raise max_len or truncate the prompt")
        r = Request(self._next_rid, prompt_ids, max_new_tokens, eos_token_id)
        self._next_rid += 1
        self._waiting.append(r)
        return r.rid

    def _admit(self):
        for slot in range(self.max_batch):
            if self._slots[slot] is not None or not self._waiting:
                continue
            r = self._waiting[0]
            need = math.ceil(min(len(r.prompt) + r.max_new,
                                 self.max_len) / self.page)
            if len(self._free_pages) < need:
                break
            self._waiting.popleft()
            pages = [self._free_pages.popleft() for _ in range(need)]
            self._slot_tables[slot, :need] = pages
            self._slot_tables[slot, need:] = pages[-1] if pages else 0
            self._lens[slot] = 0
            r.slot = slot
            self._slots[slot] = r

    def _release(self, slot):
        r = self._slots[slot]
        need = math.ceil(min(len(r.prompt) + r.max_new,
                             self.max_len) / self.page)
        for p in self._slot_tables[slot, :need]:
            self._free_pages.append(int(p))
        self._slots[slot] = None
        self._lens[slot] = 0
        r.done = True
        self._finished[r.rid] = r

    def step(self):
        """One engine token-step. Returns #active slots served."""
        self._admit()
        active = np.zeros((self.max_batch,), np.int32)
        tokens = np.zeros((self.max_batch,), np.int32)
        for slot, r in enumerate(self._slots):
            if r is None:
                continue
            active[slot] = 1
            if r.pos < len(r.prompt):
                tokens[slot] = r.prompt[r.pos]
            else:
                tokens[slot] = r.out[-1]
        if not active.any():
            return 0
        nxt, self.kp, self.vp = self._step(
            self.W, self.kp, self.vp, jnp.asarray(tokens),
            jnp.asarray(self._lens), jnp.asarray(self._slot_tables),
            jnp.asarray(active))
        nxt = np.asarray(nxt)
        for slot, r in enumerate(self._slots):
            if r is None:
                continue
            self._lens[slot] += 1
            r.pos += 1
            if r.pos >= len(r.prompt):          # past prefill: token emitted
                r.out.append(int(nxt[slot]))
                hit_eos = (r.eos is not None and r.out[-1] == r.eos)
                if (len(r.out) >= r.max_new or hit_eos or
                        self._lens[slot] >= self.max_len):
                    self._release(slot)
        return int(active.sum())

    def run_until_done(self, max_steps=10000):
        steps = 0
        while (self._waiting or any(s is not None for s in self._slots)) \
                and steps < max_steps:
            self.step()
            steps += 1
        return steps

    def result(self, rid):
        return self._finished[rid].out
