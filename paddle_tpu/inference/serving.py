"""Serving runtime for (sharded) LLMs — the role the reference fills with
the FleetExecutor actor/interceptor pipeline for multi-stage inference
(paddle/fluid/distributed/fleet_executor/carrier.cc) plus the paged
KV-cache fused ops (phi/kernels/fusion block_multi_head_attention; the
encoder/decoder split there is seq_lens_encoder vs seq_lens_decoder,
python/paddle/incubate/nn/functional/block_multihead_attention.py:33, and
sampling is in-op via phi top_p_sampling).

TPU-native design:
- TWO jitted programs serve the whole engine:
  * a PREFILL step consuming a CHUNK of prompt tokens for one slot per
    dispatch (chunk rows ride the paged-attention kernel's batch dim with
    per-row context lengths, so causal masking falls out of ctx=pos+1), and
  * a DECODE step feeding every in-flight slot its last token — token-level
    continuous batching (Orca-style).
  A P-token prompt costs ceil(P/chunk) dispatches before its first token,
  not P (the r3 engine fed one prompt token per dispatch).
- Sampling happens IN-GRAPH with per-slot parameters (greedy / temperature /
  top-k / top-p / seed), replicating models.llama._sample token-for-token so
  an engine decode with the same seed matches model.generate.
- KV lives in PAGES [L, n_pages, page, KVH, D] with host-managed per-slot
  page tables. Pages are allocated ON DEMAND: admit reserves only the
  prompt's pages and decode grows by one page at boundary crossings, so a
  `page_pool` SMALLER than the worst case (the HBM budget knob)
  oversubscribes safely — when the pool runs dry the youngest slot is
  preempted back to the waiting queue (vLLM-style recompute).
- AUTOMATIC PREFIX CACHING (`prefix_cache=True`): every FULL prompt page is
  hashed by its prefix chain (key_i = H(key_{i-1}, page_i tokens) — the
  radix-trie lookup collapsed to a chain-hash dict, SGLang-style), physical
  pages are REFCOUNTED so several slots map the same page, and admission
  skips prefill over every fully-cached page (`req.pos` jumps ahead; only
  the tail chunk dispatches). A slot writing into a page another slot still
  maps gets a COPY-ON-WRITE private page first; released pages whose
  content is cached stay resident in an LRU and are reclaimed (evicted)
  only when the free list runs dry, so preemption stays the last resort.
  Cached KV is bit-identical to what recomputation would write (same
  program, same absolute RoPE positions), so hits change dispatch counts,
  never tokens.
- Weights are extracted from the model once, stacked [L, ...] and placed
  with NamedShardings: layers sharded over the pp axis, head/ffn dims over
  the mp axis. GSPMD inserts the collectives.
"""
from __future__ import annotations

import enum
import math
import time
from collections import OrderedDict, deque

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import observability as _obs
from ..core.retry import RetryError, RetryPolicy, retry_call
from ..testing.faults import FAULTS as _faults

__all__ = ["LLMEngine", "Request", "RequestStatus", "SpecConfig",
           "prefix_page_keys"]

_MAXK = 64        # static cap for per-slot dynamic top-k filtering


def prefix_page_keys(tokens, page_size):
    """Chain key per FULL page: key_i = hash(key_{i-1}, page_i tokens).

    The prefix-cache radix lookup collapsed to one dict probe per page — a
    page is shareable only as the tail of an identical-from-position-0
    prefix (RoPE bakes absolute positions into cached K, so content alone
    is not enough).  Public because the serving front door computes the
    SAME keys to route a request to the replica whose cache already holds
    its prefix (frontend/router.py); the engine's own radix index uses
    this function too, so router affinity and engine hits can never
    disagree on hashing."""
    page_size = int(page_size)
    keys, h = [], None
    for i in range(0, (len(tokens) // page_size) * page_size, page_size):
        h = hash((h,) + tuple(int(t) for t in tokens[i:i + page_size]))
        keys.append(h)
    return keys


class RequestStatus(enum.Enum):
    """Request lifecycle. Exactly one terminal status per request:

    FINISHED   max_new_tokens (or engine max_len) reached
    EOS        the eos token was sampled
    TIMEOUT    deadline expired (waiting: shed unserved; mid-decode: the
               partial output is kept and the slot finalized cleanly)
    CANCELLED  ``cancel(rid)`` — pages released through the refcounts
    SHED       admission control refused the request at add_request
    FAILED     quarantined by step-failure isolation (``Request.error`` holds
               the underlying exception text)
    """
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    EOS = "eos"
    TIMEOUT = "timeout"
    CANCELLED = "cancelled"
    SHED = "shed"
    FAILED = "failed"

    @property
    def terminal(self):
        return self not in (RequestStatus.QUEUED, RequestStatus.RUNNING)


_TERMINAL = tuple(s for s in RequestStatus if s.terminal)


class _EngineMetrics:
    """Registry children bound once per engine (label ``engine=<seq>``).

    Every mutation is a no-op while observability is disabled, so the engine
    attributes (cache_hits, preemptions, ...) stay the always-on source of
    truth and the registry mirrors them 1:1 whenever metrics are on — the
    parity :meth:`LLMEngine.prefix_cache_stats` keeps by construction."""

    def __init__(self, label):
        e = {"engine": label}
        self.label = label
        self.ttft = _obs.SERVING_TTFT.labels(**e)
        self.token_latency = _obs.SERVING_TOKEN_LATENCY.labels(**e)
        self.queue_depth = _obs.SERVING_QUEUE_DEPTH.labels(**e)
        self.active_slots = _obs.SERVING_ACTIVE_SLOTS.labels(**e)
        self.occupancy = _obs.SERVING_OCCUPANCY.labels(**e)
        self.prefill = _obs.SERVING_DISPATCHES.labels(kind="prefill", **e)
        self.decode = _obs.SERVING_DISPATCHES.labels(kind="decode", **e)
        self.tokens = _obs.SERVING_TOKENS.labels(**e)
        self.preempt = _obs.SERVING_PREEMPTIONS.labels(**e)
        self.hits = _obs.SERVING_CACHE_EVENTS.labels(event="hit", **e)
        self.misses = _obs.SERVING_CACHE_EVENTS.labels(event="miss", **e)
        self.evictions = _obs.SERVING_CACHE_EVENTS.labels(event="eviction",
                                                          **e)
        self.cow = _obs.SERVING_CACHE_EVENTS.labels(event="cow_copy", **e)
        self.cached_pages = _obs.SERVING_CACHED_PAGES.labels(**e)
        self.reclaimable = _obs.SERVING_RECLAIMABLE_PAGES.labels(**e)
        self.free_pages = _obs.SERVING_FREE_PAGES.labels(**e)
        self.verify = _obs.SERVING_DISPATCHES.labels(kind="verify", **e)
        self.spec_proposed = _obs.SERVING_SPEC_PROPOSED.labels(**e)
        self.spec_accepted = _obs.SERVING_SPEC_ACCEPTED.labels(**e)
        self.spec_acceptance = _obs.SERVING_SPEC_ACCEPTANCE.labels(**e)
        self.terminal = {s: _obs.SERVING_TERMINALS.labels(status=s.value, **e)
                         for s in _TERMINAL}
        self.step_fail = {ph: _obs.SERVING_STEP_FAILURES.labels(phase=ph, **e)
                          for ph in ("prefill", "decode", "verify")}
        self.probes = _obs.SERVING_QUARANTINE_PROBES.labels(**e)


class Request:
    def __init__(self, rid, prompt_ids, max_new_tokens, eos_token_id=None,
                 do_sample=False, temperature=1.0, top_p=1.0, top_k=0,
                 seed=None, deadline=None):
        self.rid = rid
        self.prompt = list(int(t) for t in np.asarray(prompt_ids).reshape(-1))
        self.prompt0 = list(self.prompt)   # original; preemption re-folds
        self.max_new = int(max_new_tokens)
        self.eos = eos_token_id
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.top_k = int(top_k)
        self.seed = seed
        self.out: list[int] = []
        self.pos = 0                 # prompt tokens already prefilled
        self.slot = None
        self.done = False
        self.admit_seq = -1          # preemption picks the youngest
        self.t_submit = time.perf_counter()
        # absolute wall deadline; expiry sheds a waiting request and cleanly
        # finalizes a decoding one (both terminal status TIMEOUT)
        self.deadline = (None if deadline is None
                         else self.t_submit + float(deadline))
        self.status = RequestStatus.QUEUED
        self.error = None            # exception text when status is FAILED
        self.t_finish = None
        self.ttft = None             # seconds to first generated token
        self.prefill_dispatches = 0  # prefill programs dispatched for us
        self.cached_tokens = 0       # prompt tokens served from prefix cache
        self.cache_keys = ()         # chain keys of the prompt's full pages
        self.stream_pos = 0          # tokens already handed to new_tokens()


def _rope(x, pos, theta):
    """neox-style RoPE at integer positions pos [B] (x [B, Hn, D])."""
    D = x.shape[-1]
    inv = 1.0 / (theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    freqs = pos.astype(jnp.float32)[:, None] * inv[None, :]      # [B, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)               # [B, D]
    s, c = jnp.sin(emb)[:, None, :], jnp.cos(emb)[:, None, :]
    xf = x.astype(jnp.float32)
    half = D // 2
    rot = jnp.concatenate([-xf[..., half:], xf[..., :half]], axis=-1)
    return (xf * c + rot * s).astype(x.dtype)


def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32)).astype(
        x.dtype)


def _sample_row(logits, greedy, temp, topp, topk, seed):
    """One row of in-graph sampling, replicating models.llama._sample +
    ops.top_p_sampling (same filter order, same sort, same categorical
    key/shape) so a SEEDED top_p<1 engine decode == model.generate.
    (At top_p>=1.0, generate falls through to ops.multinomial on the global
    RNG stream, which ignores the seed — no parity is possible there by
    construction.) logits [V] f32; scalars traced."""
    maxk = min(_MAXK, logits.shape[-1])
    amax = jnp.argmax(logits)
    l = logits / jnp.where(temp > 0, temp, 1.0)
    probs = jax.nn.softmax(l)
    # top-k (0 = off): zero everything below the k-th largest prob
    kvals, _ = jax.lax.top_k(probs, maxk)
    thresh = kvals[jnp.clip(topk - 1, 0, maxk - 1)]
    probs = jnp.where((topk > 0) & (probs < thresh), 0.0, probs)
    probs = probs / jnp.sum(probs)
    # top-p over the full sorted vocab (ops.top_p_sampling's formulation)
    sort_idx = jnp.argsort(-probs)
    sorted_p = probs[sort_idx]
    cum = jnp.cumsum(sorted_p)
    keep = jnp.where(topp < 1.0, (cum - sorted_p) < topp, sorted_p >= 0)
    filtered = jnp.where(keep, sorted_p, 0.0)
    filtered = filtered / jnp.sum(filtered)
    key = jax.random.PRNGKey(seed)
    # [1, V] shape matches the b=1 categorical in ops.top_p_sampling, so the
    # gumbel draw is bit-identical at equal keys
    choice = jax.random.categorical(
        key, jnp.log(jnp.maximum(filtered, 1e-30))[None, :], axis=-1)[0]
    tok = sort_idx[choice]
    return jnp.where(greedy > 0, amax, tok).astype(jnp.int32)


def _ceil_pow2(n):
    return 1 << max(0, int(n) - 1).bit_length() if n > 1 else 1


class SpecConfig:
    """Speculative-decoding knob (``LLMEngine(spec_decode=SpecConfig())``).

    max_draft: most draft tokens proposed per request per verify step.
    ngram_max / ngram_min: window bounds for the self-drafting n-gram
        proposer — the request's current n-token suffix (longest n first)
        is matched against its own earlier prompt+generated tokens, and
        the tokens that followed the most recent match become the draft.
        Free (no extra weights); wins on repetitive structure (code,
        retrieved context, templated text).
    draft_model: optional small LlamaForCausalLM replacing the n-gram
        proposer — greedy continuation of the request's token history.
    adaptive: learn the verify dispatch's cost curve t(rows) = RTT+rows*c
        (separately from the decode-block auto-fit: a verify step consumes
        a VARIABLE number of tokens) and pick the draft length maximizing
        expected accepted tokens per second under the observed acceptance
        rate; False always proposes max_draft."""

    def __init__(self, max_draft=4, ngram_max=3, ngram_min=1,
                 draft_model=None, adaptive=True):
        if int(max_draft) < 1:
            raise ValueError("max_draft must be >= 1")
        if int(ngram_min) < 1 or int(ngram_max) < int(ngram_min):
            raise ValueError("need 1 <= ngram_min <= ngram_max")
        self.max_draft = int(max_draft)
        self.ngram_max = int(ngram_max)
        self.ngram_min = int(ngram_min)
        self.draft_model = draft_model
        self.adaptive = bool(adaptive)


class _NgramProposer:
    """Self-drafting proposer: find the most recent earlier occurrence of
    the sequence's current suffix (longest n in [ngram_min, ngram_max]
    wins) and propose the tokens that followed that occurrence."""

    def __init__(self, cfg):
        self.cfg = cfg

    def propose(self, tokens, k):
        n_tok = len(tokens)
        hi = min(self.cfg.ngram_max, n_tok - 1)
        for n in range(hi, self.cfg.ngram_min - 1, -1):
            suffix = tokens[n_tok - n:]
            for i in range(n_tok - n - 1, -1, -1):
                if tokens[i:i + n] == suffix:
                    cont = tokens[i + n:i + n + k]
                    if cont:
                        return list(cont)
        return []


class _DraftModelProposer:
    """Draft-model proposer: greedy continuation from a small model. The
    draft recomputes from the full token history each call (no persistent
    draft KV) — drafts are short and the draft model is small, so clarity
    beats cache bookkeeping here."""

    def __init__(self, model):
        self.model = model

    def propose(self, tokens, k):
        from .. import to_tensor
        ids = to_tensor(np.asarray([tokens], np.int64))
        out = self.model.generate(ids, max_new_tokens=k, do_sample=False)
        seq = np.asarray(out._data).reshape(-1)
        return [int(t) for t in seq[len(tokens):]]


class _TransientStep(Exception):
    """Private wrapper around a transient step error so :func:`retry_call`
    retries exactly those — any non-transient error escapes the retry loop
    unwrapped and falls through to quarantine isolation."""

    def __init__(self, err):
        super().__init__(str(err))
        self.err = err


class LLMEngine:
    """Continuous-batching paged-KV engine over a LlamaForCausalLM."""

    _engine_seq = 0   # observability label: one series set per engine

    def __init__(self, model, mesh=None, mp_axis="mp", pp_axis="pp",
                 max_batch=4, max_len=256, page_size=16, prefill_chunk=32,
                 page_pool=None, decode_block=1, use_kernel=None, seed=0,
                 kv_cache_dtype="auto", decode_block_max=32,
                 prefix_cache=False, spec_decode=None, max_waiting=None,
                 shed_min_free_ratio=0.0, default_deadline=None,
                 step_retry=None, debug_refcount_audit=False):
        """page_pool: usable KV pages (the HBM budget). Defaults to the
        worst case (max_batch * ceil(max_len/page)); set it SMALLER to
        oversubscribe — on-demand growth means slots only claim what they
        use, and a dry pool preempts the youngest slot (recompute).

        prefix_cache: automatic prefix caching (vLLM shared pages + CoW,
        SGLang-style chain-hash lookup). Full prompt pages are hashed by
        (prefix chain, page tokens) and refcounted; a later request whose
        prompt starts with a cached page chain maps those physical pages
        into its table and skips their prefill entirely (at least the final
        prompt token always re-prefills — its logits sample the first output
        token, and when that token's page is still shared the write goes
        through a copy-on-write private page). Released-but-cached pages
        park in an LRU and are evicted only when the free list runs dry.
        Counters: ``cache_hits`` / ``cache_misses`` (pages, at admission),
        ``cache_evictions``, ``cache_cow_copies`` — see
        :meth:`prefix_cache_stats`. Token streams are byte-identical to a
        ``prefix_cache=False`` engine at the same seeds; only dispatch
        counts and TTFT change. (One caveat shared with generate(): a
        do_sample request WITHOUT a fixed seed draws from the engine's
        global seed counter, which advances once per prefill dispatch —
        fewer dispatches shift later seedless draws. Seeded and greedy
        requests are unaffected.)

        decode_block: max decode steps fused into one dispatch (power-of-two
        blocks are chosen per step, shrinking near max_new; eos-bearing
        requests force 1). Raise it when dispatch latency, not throughput,
        dominates (e.g. a remote/tunneled runtime) — or pass "auto": the
        engine then samples wall time at two block sizes, solves the
        dispatch model t(k) = RTT + k*c for the session's actual round-trip
        latency and per-token device time, and picks the power-of-two block
        where RTT costs <= ~25% of device time (re-estimated as timing
        samples accumulate, capped at decode_block_max).

        kv_cache_dtype: "auto" stores pages in the weight dtype; "int8"
        quantizes K/V pages per-(token, kv-head) with f32 scales (reference:
        incubate block_multihead_attention cache_*_quant_scales, dynamic
        mode) — pages cost (D + 4)/(2*D) of bf16 bytes (~0.52 at
        head_dim=128), so the same HBM budget holds ~2x the tokens /
        concurrent slots.

        spec_decode: a :class:`SpecConfig` enables speculative decoding —
        each step a proposer drafts up to max_draft continuation tokens per
        request (self-drafting n-gram suffix match by default, or a small
        draft model) and ONE target-model forward scores the pending token
        plus every draft at consecutive positions (multi-query paged
        attention). Acceptance is the standard token-match rule — the
        longest draft prefix that equals what the target would have
        sampled — which for the deterministic proposers here is exact
        rejection sampling, so greedy and fixed-seed sampled outputs are
        token-identical to a spec-off engine. Accepted tokens all land in
        one dispatch (up to max_draft+1 tokens/step); rejected drafts roll
        their provisional KV pages back through the page-pool refcounts
        (a partially-filled page is truncated, never shared). Steps where
        no request has a draft fall through to the normal decode-block
        path. Counters: :meth:`spec_stats`, plus ``spec_proposed_total`` /
        ``spec_accepted_total`` / acceptance histogram in the registry.

        Fault tolerance (see :meth:`health` for the counter snapshot):

        max_waiting: admission-control queue bound — add_request beyond it
        returns a request already terminal with status SHED (None keeps the
        legacy unbounded queue).
        shed_min_free_ratio: page-pressure watermark — while the backlog is
        non-empty and (free + reclaimable) pages fall below this fraction of
        the pool, new requests are shed.
        default_deadline: seconds each request may spend end-to-end unless
        add_request overrides; expiry sheds waiting requests and cleanly
        finalizes decoding ones (status TIMEOUT, partial output kept).
        step_retry: :class:`~paddle_tpu.core.retry.RetryPolicy` for
        TRANSIENT step errors (an exception with a truthy ``transient``
        attribute, e.g. an injected transient fault) — the step is retried
        with backoff before failure isolation kicks in. Default: 3 attempts,
        10ms base.  Non-transient step errors never crash the loop: the
        failing dispatch is re-run one slot at a time and the slot that
        fails alone is quarantined (terminal FAILED, pages freed through the
        refcounts) while the rest keep serving.
        debug_refcount_audit: run :meth:`audit_refcounts` after every step
        and raise on any page-accounting violation (tier-1 chaos tests keep
        this on to prove no failure path leaks pages)."""
        cfg = model.config
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len
        self.page = page_size
        self.chunk = int(prefill_chunk)
        self.pages_per_slot = math.ceil(max_len / page_size)
        if page_pool is None:
            page_pool = max_batch * self.pages_per_slot
        if page_pool < self.pages_per_slot:
            raise ValueError("page_pool must cover at least one max_len "
                             f"request ({self.pages_per_slot} pages)")
        # +1: a trash page absorbing the (masked-out) writes of inactive slots
        self.n_pages = int(page_pool) + 1
        self.trash_page = self.n_pages - 1
        self.mesh = mesh
        L = cfg.num_hidden_layers
        H = cfg.hidden_size
        nh, kvh = cfg.num_attention_heads, cfg.num_key_value_heads
        D = H // nh
        self.nh, self.kvh, self.D = nh, kvh, D
        if use_kernel is None:
            use_kernel = (mesh is None and
                          jax.devices()[0].platform in ("tpu", "axon"))
        self.use_kernel = use_kernel

        def wb(lin):        # Linear stores weight [in, out]
            return np.asarray(lin.weight._data)

        lay = model.llama.layers
        W = {
            "embed": np.asarray(model.llama.embed_tokens.weight._data),
            "norm": np.asarray(model.llama.norm.weight._data),
            "wq": np.stack([wb(l.self_attn.q_proj) for l in lay]),
            "wk": np.stack([wb(l.self_attn.k_proj) for l in lay]),
            "wv": np.stack([wb(l.self_attn.v_proj) for l in lay]),
            "wo": np.stack([wb(l.self_attn.o_proj) for l in lay]),
            "ln1": np.stack([np.asarray(l.input_layernorm.weight._data)
                             for l in lay]),
            "ln2": np.stack([np.asarray(
                l.post_attention_layernorm.weight._data) for l in lay]),
            "wg": np.stack([wb(l.mlp.gate_proj) for l in lay]),
            "wu": np.stack([wb(l.mlp.up_proj) for l in lay]),
            "wd": np.stack([wb(l.mlp.down_proj) for l in lay]),
        }
        W["head"] = (np.asarray(model.lm_head.weight._data)
                     if model.lm_head is not None else W["embed"].T)
        dtype = W["wq"].dtype
        if mesh is not None:
            pp = pp_axis if pp_axis in mesh.axis_names else None
            mp = mp_axis if mp_axis in mesh.axis_names else None

            def put(name, arr, spec):
                return jax.device_put(jnp.asarray(arr),
                                      NamedSharding(mesh, spec))
            specs = {
                "embed": P(), "norm": P(), "head": P(None, mp),
                "wq": P(pp, None, mp), "wk": P(pp, None, mp),
                "wv": P(pp, None, mp), "wo": P(pp, mp, None),
                "ln1": P(pp, None), "ln2": P(pp, None),
                "wg": P(pp, None, mp), "wu": P(pp, None, mp),
                "wd": P(pp, mp, None),
            }
            self.W = {k: put(k, v, specs[k]) for k, v in W.items()}
            cache_spec = NamedSharding(mesh, P(pp))
        else:
            self.W = {k: jnp.asarray(v) for k, v in W.items()}
            cache_spec = None
        self.kv_quant = (kv_cache_dtype == "int8")
        page_dtype = jnp.int8 if self.kv_quant else dtype
        kp = jnp.zeros((L, self.n_pages, page_size, kvh, D), page_dtype)
        vp = jnp.zeros_like(kp)
        if cache_spec is not None:
            kp = jax.device_put(kp, cache_spec)
            vp = jax.device_put(vp, cache_spec)
        if self.kv_quant:
            ks = jnp.zeros((L, self.n_pages, page_size, kvh), jnp.float32)
            vs = jnp.zeros_like(ks)
            if cache_spec is not None:
                ks = jax.device_put(ks, cache_spec)
                vs = jax.device_put(vs, cache_spec)
            self.cache = (kp, vp, ks, vs)
        else:
            self.cache = (kp, vp)

        # host scheduler state (trash page is never allocated)
        self._free_pages = deque(range(self.n_pages - 1))
        # prefix cache: refcounts + chain-hash index + reclaimable LRU.
        # With prefix_cache=False nothing is ever hashed, so every released
        # page goes straight back to _free_pages (legacy behavior).
        self.prefix_cache = bool(prefix_cache)
        # optional (event, chain_key) callback — the frontend router
        # subscribes here to mirror this engine's radix index ("register" on
        # page registration, "evict" on LRU reclaim) into its per-replica
        # affinity index.  Called from inside step(); must be cheap and
        # must not raise.
        self.cache_event_listener = None
        self._page_ref = np.zeros(self.n_pages, np.int64)
        self._page_key: dict = {}          # physical page -> chain key
        self._key_page: dict = {}          # chain key -> physical page
        self._lru: OrderedDict = OrderedDict()  # cached, refcount==0 pages
        self.cache_hits = 0                # pages served from cache (admit)
        self.cache_misses = 0              # full prompt pages not cached
        self.cache_evictions = 0           # cached pages reclaimed from LRU
        self.cache_cow_copies = 0          # copy-on-write page copies
        self.prefill_dispatches = 0        # total prefill programs run
        self._copy_page_fn = None
        self._slots: list = [None] * max_batch
        self._slot_tables = np.zeros((max_batch, self.pages_per_slot),
                                     np.int32)
        self._lens = np.zeros((max_batch,), np.int32)
        self._n_alloc = np.zeros((max_batch,), np.int32)
        self._waiting: deque = deque()
        self._finished: dict = {}
        self._next_rid = 0
        self._admit_seq = 0
        self._seed_counter = np.int64(seed) * 1_000_003
        self.preemptions = 0
        self._auto_block = decode_block == "auto"
        if self._auto_block:
            self.decode_block = max(1, int(decode_block_max))
            self._block_target = 1          # sample k=1 first, then k=2
            self._block_samples: dict = {}  # k -> recent wall dts
            self._block_n = 0               # total samples recorded
        else:
            self.decode_block = max(1, int(decode_block))
        self._decode_programs: dict = {}
        # speculative decoding (off unless spec_decode is a SpecConfig)
        self._spec = spec_decode
        if self._spec is not None:
            self._proposer = (
                _DraftModelProposer(self._spec.draft_model)
                if self._spec.draft_model is not None
                else _NgramProposer(self._spec))
        self._verify_programs: dict = {}
        self._spec_samples: dict = {}   # verify rows -> recent wall dts
        self._spec_accept_ema = None    # EMA of per-step acceptance ratio
        self.spec_proposed = 0          # draft tokens sent to verification
        self.spec_accepted = 0          # draft tokens that matched
        self.spec_emitted = 0           # tokens emitted by verify steps
        self.spec_dispatches = 0        # verify programs dispatched
        # fault tolerance: admission control, deadlines, failure isolation
        self.max_waiting = None if max_waiting is None else int(max_waiting)
        self.shed_min_free_ratio = float(shed_min_free_ratio)
        self.default_deadline = default_deadline
        self.debug_refcount_audit = bool(debug_refcount_audit)
        self._step_retry = (step_retry if step_retry is not None else
                            RetryPolicy(max_attempts=3, base_delay=0.01,
                                        max_delay=0.25, seed=seed))
        self._any_deadline = default_deadline is not None
        self._step_phase = ("admit", ())
        self.shed_requests = 0          # refused by admission control
        self.timeouts = 0               # deadline expiries (waiting + active)
        self.cancels = 0                # cancel(rid) that found the request
        self.quarantined = 0            # requests isolated as FAILED
        self.step_failures = 0          # step dispatches that raised
        self.step_retries = 0           # transient-path retry invocations
        self.quarantine_probes = 0      # single-slot isolation probes run
        self._m = _EngineMetrics(str(LLMEngine._engine_seq))
        LLMEngine._engine_seq += 1
        self._prefill = self._build_prefill()

    # ---------------------------------------------------------------- layers
    def _layer_fn(self, page_idx, within, tables, ctx, pos, mq=None):
        """Shared per-layer body for decode, prefill, and speculative
        verification (they differ only in how many rows ride the batch dim
        and where those rows' pages are). With ``mq=(B, Q)`` the flat rows
        are B sequences x Q consecutive query positions and attention goes
        through the multi-query kernel (tables [B, S]; ctx [B] is row 0's
        context length, row j sees ctx+j); KV writes stay per-flat-row."""
        nh, kvh, D = self.nh, self.kvh, self.D
        eps = self.cfg.rms_norm_eps
        theta = self.cfg.rope_theta
        use_kernel = self.use_kernel

        quant = self.kv_quant

        def layer(carry, wl):
            from ..ops.pallas.paged_attention import (
                paged_attention, paged_attention_multiquery,
                paged_attention_multiquery_ref, paged_attention_ref,
                quantize_kv)
            x, = carry
            h = _rms(x, wl["ln1"], eps)
            q = (h @ wl["wq"]).reshape(-1, nh, D)
            k = (h @ wl["wk"]).reshape(-1, kvh, D)
            v = (h @ wl["wv"]).reshape(-1, kvh, D)
            q = _rope(q, pos, theta)
            k = _rope(k, pos, theta)
            if mq is None:
                attn = paged_attention if use_kernel else paged_attention_ref
            else:
                Bq, Q = mq
                base = (paged_attention_multiquery if use_kernel
                        else paged_attention_multiquery_ref)

                def attn(qx, kp, vp, tb, cl, **kw):
                    out = base(qx.reshape(Bq, Q, nh, D), kp, vp, tb, cl,
                               **kw)
                    return out.reshape(Bq * Q, nh, D)
            if quant:
                kq, ksc = quantize_kv(k)
                vq, vsc = quantize_kv(v)
                kpl = wl["kp"].at[page_idx, within].set(kq)
                vpl = wl["vp"].at[page_idx, within].set(vq)
                ksl = wl["kps"].at[page_idx, within].set(ksc)
                vsl = wl["vps"].at[page_idx, within].set(vsc)
                att = attn(q, kpl, vpl, tables, ctx,
                           k_scales=ksl, v_scales=vsl)
                new_cache = (kpl, vpl, ksl, vsl)
            else:
                kpl = wl["kp"].at[page_idx, within].set(k)
                vpl = wl["vp"].at[page_idx, within].set(v)
                att = attn(q, kpl, vpl, tables, ctx)
                new_cache = (kpl, vpl)
            x = x + att.reshape(-1, nh * D) @ wl["wo"]
            h = _rms(x, wl["ln2"], eps)
            gate = h @ wl["wg"]
            up = h @ wl["wu"]
            x = x + (jax.nn.silu(gate.astype(jnp.float32)).astype(
                up.dtype) * up) @ wl["wd"]
            return (x,), new_cache

        return layer

    def _scan_layers(self, W, cache, x, layer):
        per_layer = {k: W[k] for k in
                     ("wq", "wk", "wv", "wo", "ln1", "ln2",
                      "wg", "wu", "wd")}
        per_layer["kp"], per_layer["vp"] = cache[0], cache[1]
        if len(cache) == 4:
            per_layer["kps"], per_layer["vps"] = cache[2], cache[3]
        (x,), new_cache = jax.lax.scan(layer, (x,), per_layer)
        return x, new_cache

    # ------------------------------------------------------------------ step
    def _build_decode(self, K):
        """K decode steps fused into ONE dispatch (token feedback stays
        in-graph via lax.scan) — through a remote dispatch path each host
        round trip costs RTT, which a per-token loop pays in full; a K-block
        pays RTT/K. The host sees the K sampled tokens afterwards, so eos
        requests cap K at 1 (every token must be inspected). Mirrors
        generate()'s tokens_per_dispatch."""
        cfg = self.cfg
        page = self.page
        eps = cfg.rms_norm_eps
        trash = self.trash_page

        def block(W, cache, tokens, lens, tables, active,
                  greedy, temp, topp, topk, seeds, fold):
            # tokens [B] int32; lens [B] tokens already cached; tables
            # [B, S] page ids; active [B] 0/1; sampling params [B].
            # fold [B]: 1 -> vary the sampling key per block step (seedless
            # requests); 0 -> reuse it (fixed-seed generate parity).
            def one(carry, i):
                tokens, lens, cache = carry
                x = W["embed"][tokens]                   # [B, H]
                pos = lens.astype(jnp.int32)
                page_idx = jnp.take_along_axis(
                    tables, (pos // page)[:, None], axis=1)[:, 0]
                # inactive slots write into the trash page, never a live one
                page_idx = jnp.where(active > 0, page_idx, trash)
                within = pos % page
                ctx = jnp.where(active > 0, pos + 1, 1).astype(jnp.int32)
                layer = self._layer_fn(page_idx, within, tables, ctx, pos)
                x, cache = self._scan_layers(W, cache, x, layer)
                h = _rms(x, W["norm"], eps)
                logits = h.astype(jnp.float32) @ W["head"].astype(
                    jnp.float32)
                # one vmapped sampler, not B inlined sort/cumsum subgraphs
                nxt = jax.vmap(_sample_row)(logits, greedy, temp, topp,
                                            topk, seeds + i * fold)
                tokens = jnp.where(active > 0, nxt, tokens)
                lens = lens + (active > 0).astype(lens.dtype)
                return (tokens, lens, cache), nxt

            (_, _, cache2), toks = jax.lax.scan(
                one, (tokens, lens, cache),
                jnp.arange(K, dtype=jnp.int32))
            return toks, cache2                          # toks [K, B]

        return jax.jit(block, donate_argnums=(1,))

    def _build_prefill(self):
        cfg = self.cfg
        page = self.page
        eps = cfg.rms_norm_eps
        trash = self.trash_page
        C = self.chunk

        def prefill(W, cache, tokens, start, table, n_valid,
                    greedy, temp, topp, topk, seed):
            # tokens [C] int32 (one slot's prompt chunk, zero-padded);
            # start scalar; table [S]; n_valid scalar <= C. Chunk rows ride
            # the paged-attention BATCH dim: row i gets ctx = start+i+1, so
            # in-chunk causality and attention to the already-cached prefix
            # both fall out of the per-row context length.
            x = W["embed"][tokens]                       # [C, H]
            offs = jnp.arange(C, dtype=jnp.int32)
            pos = start.astype(jnp.int32) + offs
            valid = offs < n_valid
            page_idx = table[pos // page]
            page_idx = jnp.where(valid, page_idx, trash)
            within = pos % page
            ctx = jnp.where(valid, pos + 1, 1).astype(jnp.int32)
            tables = jnp.broadcast_to(table[None, :], (C, table.shape[0]))
            layer = self._layer_fn(page_idx, within, tables, ctx, pos)
            x, cache2 = self._scan_layers(W, cache, x, layer)
            h = _rms(x, W["norm"], eps)
            last = h[jnp.maximum(n_valid - 1, 0)]
            logits = last.astype(jnp.float32) @ W["head"].astype(jnp.float32)
            nxt = _sample_row(logits, greedy, temp, topp, topk, seed)
            return nxt, cache2

        return jax.jit(prefill, donate_argnums=(1,))

    def _build_verify(self, Kv):
        """ONE forward scoring Kv consecutive positions per request — the
        speculative-decoding verifier. Row 0 carries the pending token
        (what plain decode would feed), rows 1..n the proposed drafts;
        sampling row j yields the target model's token AFTER draft j, so
        the host accepts the longest draft prefix matching the sampled
        tokens and emits accepted+1 tokens from a single dispatch. All Kv
        KV writes land in-graph; the host rolls back pages past the
        accepted point afterwards (attention masks by context length, so
        stale writes beyond a slot's length are never attended)."""
        cfg = self.cfg
        page = self.page
        eps = cfg.rms_norm_eps
        trash = self.trash_page
        B = self.max_batch

        def verify(W, cache, tokens, lens, tables, n_rows,
                   greedy, temp, topp, topk, seeds, fold):
            # tokens [B, Kv] int32 (row 0 = pending, 1.. = drafts, rest
            # padding); lens [B] tokens already cached; n_rows [B] valid
            # rows (0 = inactive slot); sampling params [B] as in decode.
            row_j = jnp.tile(jnp.arange(Kv, dtype=jnp.int32), B)  # [B*Kv]

            def rep(a):
                return jnp.repeat(a, Kv)

            pos = rep(lens.astype(jnp.int32)) + row_j
            valid = row_j < rep(n_rows)
            page_idx = jnp.take_along_axis(
                tables, (pos // page).reshape(B, Kv), axis=1).reshape(-1)
            page_idx = jnp.where(valid, page_idx, trash)
            within = pos % page
            # row 0 of an active request sees lens+1 tokens (its own write
            # included); the multi-query kernel extends by +j per row
            cl = jnp.where(n_rows > 0, lens + 1, 1).astype(jnp.int32)
            x = W["embed"][tokens.reshape(-1)]            # [B*Kv, H]
            layer = self._layer_fn(page_idx, within, tables, cl, pos,
                                   mq=(B, Kv))
            x, cache2 = self._scan_layers(W, cache, x, layer)
            h = _rms(x, W["norm"], eps)
            logits = h.astype(jnp.float32) @ W["head"].astype(jnp.float32)
            # seed schedule mirrors the decode block's `seeds + i*fold`:
            # emitted token #j of this step draws the key step #j of a
            # non-speculative block would have drawn, so fixed-seed
            # (fold=0) and greedy requests stay token-exact vs spec-off
            seeds_rep = rep(seeds) + row_j * rep(fold)
            toks = jax.vmap(_sample_row)(
                logits, rep(greedy), rep(temp), rep(topp), rep(topk),
                seeds_rep)
            return toks.reshape(B, Kv), cache2

        return jax.jit(verify, donate_argnums=(1,))

    # ------------------------------------------------------------- scheduling
    def add_request(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
                    do_sample=False, temperature=1.0, top_p=1.0, top_k=0,
                    seed=None, deadline=None):
        """Submit a request; returns its rid.  ``deadline`` (seconds,
        default ``default_deadline``) bounds its total wall time.  Admission
        control may refuse it: the rid is still returned, but the request is
        already terminal with :attr:`RequestStatus.SHED` (check
        :meth:`status`) — malformed arguments still raise."""
        n_prompt = int(np.asarray(prompt_ids).reshape(-1).shape[0])
        if n_prompt == 0:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if n_prompt + int(max_new_tokens) > self.max_len:
            # admitting would silently truncate at max_len (ADVICE r3): the
            # caller must choose — raise max_len or shrink the request
            raise ValueError(
                f"prompt ({n_prompt}) + max_new_tokens ({max_new_tokens}) "
                f"> engine max_len ({self.max_len})")
        vocab = self.cfg.vocab_size
        if int(top_k) > min(_MAXK, vocab):
            raise ValueError(
                f"top_k={top_k} exceeds the engine's in-graph cap "
                f"{min(_MAXK, vocab)} (static top-k window)")
        if deadline is None:
            deadline = self.default_deadline
        r = Request(self._next_rid, prompt_ids, max_new_tokens, eos_token_id,
                    do_sample=do_sample, temperature=temperature,
                    top_p=top_p, top_k=top_k, seed=seed, deadline=deadline)
        self._next_rid += 1
        if deadline is not None:
            self._any_deadline = True
        if self._should_shed():
            self._finalize(r, RequestStatus.SHED)
        else:
            self._waiting.append(r)
        return r.rid

    # ----------------------------------------------------- request lifecycle
    def _should_shed(self):
        """Watermark admission control over the same gauges metrics()
        exports: a bounded waiting queue, plus a page-pressure floor that
        sheds while a backlog already exists (an idle engine always admits —
        a single fresh request can still run via preemption)."""
        if self.max_waiting is not None \
                and len(self._waiting) >= self.max_waiting:
            return True
        if self.shed_min_free_ratio > 0.0 and self._waiting:
            avail = len(self._free_pages) + len(self._lru)
            if avail < self.shed_min_free_ratio * (self.n_pages - 1):
                return True
        return False

    def _finalize(self, r, status, error=None):
        """Move ``r`` to its typed terminal status (the ONLY path into
        ``_finished``), mirroring the terminal counters."""
        r.status = status
        r.done = True
        r.slot = None
        if error is not None:
            r.error = f"{type(error).__name__}: {error}"
        r.t_finish = time.perf_counter()
        self._finished[r.rid] = r
        if status is RequestStatus.SHED:
            self.shed_requests += 1
        elif status is RequestStatus.TIMEOUT:
            self.timeouts += 1
        elif status is RequestStatus.CANCELLED:
            self.cancels += 1
        elif status is RequestStatus.FAILED:
            self.quarantined += 1
        self._m.terminal[status].inc()

    def cancel(self, rid):
        """Cancel a request wherever it is: waiting (dequeued) or mid-serve
        (slot released — pages return through the refcount machinery, so
        prefix-cache pages other slots share stay live).  Returns True if
        the request was found live; False if unknown or already terminal."""
        for i, r in enumerate(self._waiting):
            if r.rid == rid:
                del self._waiting[i]
                self._finalize(r, RequestStatus.CANCELLED)
                return True
        for slot, r in enumerate(self._slots):
            if r is not None and r.rid == rid:
                self._release(slot, RequestStatus.CANCELLED)
                return True
        return False

    def _expire_deadlines(self):
        """Deadline sweep at step entry: expired waiting requests are shed
        unserved; an expired in-flight request finalizes cleanly (partial
        output kept, pages released).  Both end TIMEOUT."""
        now = time.perf_counter()
        if self._waiting:
            expired = [r for r in self._waiting
                       if r.deadline is not None and now > r.deadline]
            if expired:
                self._waiting = deque(r for r in self._waiting
                                      if not (r.deadline is not None
                                              and now > r.deadline))
                for r in expired:
                    self._finalize(r, RequestStatus.TIMEOUT)
        for slot, r in enumerate(self._slots):
            if r is not None and r.deadline is not None and now > r.deadline:
                self._release(slot, RequestStatus.TIMEOUT)

    # ------------------------------------------------------ page accounting
    def _page_keys(self, tokens):
        """Chain keys of ``tokens``' full pages (see
        :func:`prefix_page_keys` — shared with the frontend router)."""
        return prefix_page_keys(tokens, self.page)

    def _ref_page(self, p):
        self._page_ref[p] += 1
        self._lru.pop(p, None)        # referenced again: not reclaimable

    def _unref_page(self, p):
        self._page_ref[p] -= 1
        if self._page_ref[p] > 0:
            return
        if p in self._page_key:       # content cached: park reclaimable
            self._lru[p] = None
            self._lru.move_to_end(p)
        else:
            self._free_pages.append(p)

    def _alloc_page(self):
        """A writable page with refcount 1: free list first, then LRU
        eviction of the oldest cached-but-unreferenced page. Returns None
        when both are dry (the caller preempts — last resort)."""
        if _faults.active and _faults.fire("serving.page_alloc") is not None:
            return None               # injected allocation failure (dry pool)
        if self._free_pages:
            p = self._free_pages.popleft()
        elif self._lru:
            p, _ = self._lru.popitem(last=False)
            key = self._page_key.pop(p)
            self._key_page.pop(key, None)
            self.cache_evictions += 1
            self._m.evictions.inc()
            if self.cache_event_listener is not None:
                self.cache_event_listener("evict", key)
        else:
            return None
        self._page_ref[p] = 1
        return p

    def _copy_page(self, src, dst):
        """Device-side copy of one physical KV page (all layers, K and V,
        int8 scales included) — the copy half of copy-on-write."""
        if self._copy_page_fn is None:
            def cp(cache, s, d):
                return tuple(a.at[:, d].set(a[:, s]) for a in cache)
            self._copy_page_fn = jax.jit(cp, donate_argnums=(0,))
        self.cache = self._copy_page_fn(
            self.cache, jnp.asarray(np.int32(src)), jnp.asarray(np.int32(dst)))
        self.cache_cow_copies += 1
        self._m.cow.inc()

    def _cow_unshare(self, slot, start, n):
        """Copy-on-write before a prefill write into [start, start+n): any
        touched page another slot still maps (refcount > 1) gets a private
        copy so the write can't clobber the shared prefix. Hit on exactly
        one path: a fully-cached prompt re-prefills its final token into the
        last shared page."""
        for j in range(start // self.page, (start + n - 1) // self.page + 1):
            p = int(self._slot_tables[slot, j])
            while int(self._page_ref[p]) > 1:
                q = self._alloc_page()
                if q is None:
                    # preemption may release the OTHER reference, making the
                    # copy unnecessary — the while re-checks
                    if not self._preempt_youngest(excluding=slot):
                        raise RuntimeError(
                            "page pool exhausted during copy-on-write — "
                            "engine misconfigured (max_len vs page pool)")
                    continue
                self._copy_page(p, q)
                self._page_ref[p] -= 1
                self._slot_tables[slot, j] = q
                if j == int(self._n_alloc[slot]) - 1:
                    self._slot_tables[slot, j + 1:] = q   # repoint padding
                p = q

    def _register_pages(self, slot, r):
        """Hash-register every completed full prompt page of this slot so
        later requests can hit it. First registration wins; a page whose
        content another physical page already serves stays private."""
        for j in range(int(self._lens[slot]) // self.page):
            p = int(self._slot_tables[slot, j])
            if p in self._page_key:
                continue                  # hit page / already registered
            key = r.cache_keys[j]
            if key in self._key_page:
                continue
            self._page_key[p] = key
            self._key_page[key] = p
            if self.cache_event_listener is not None:
                self.cache_event_listener("register", key)

    def _admit(self):
        for slot in range(self.max_batch):
            if self._slots[slot] is not None or not self._waiting:
                continue
            r = self._waiting[0]
            # on-demand paging: reserve only the PROMPT's pages; decode
            # grows page-by-page (cf. the r3 engine's worst-case
            # prompt+max_new reservation, which gave paging no benefit)
            need = math.ceil(len(r.prompt) / self.page)
            keys = self._page_keys(r.prompt) if self.prefix_cache else []
            hits = []
            for key in keys:
                p = self._key_page.get(key)
                if p is None:
                    break
                hits.append(p)
            # pages admission must newly claim; hit pages sitting in the LRU
            # are about to be re-referenced, so they are NOT allocatable
            fresh = need - len(hits)
            avail = (len(self._free_pages) + len(self._lru)
                     - sum(1 for p in hits if p in self._lru))
            if avail < fresh:
                break
            self._waiting.popleft()
            pages = []
            for p in hits:                # ref hits BEFORE allocating fresh
                self._ref_page(p)         # pages so eviction can't take them
                pages.append(p)
            aborted = False
            for _ in range(fresh):
                p = self._alloc_page()
                if p is None:
                    # allocation failed mid-admission (injected fault, or a
                    # racing claim): roll the claimed pages back and requeue
                    # the request at the front — never a half-built table
                    for q in pages:
                        self._unref_page(q)
                    self._waiting.appendleft(r)
                    aborted = True
                    break
                pages.append(p)
            if aborted:
                break
            self._slot_tables[slot, :need] = pages
            self._slot_tables[slot, need:] = pages[-1]
            self._n_alloc[slot] = need
            # skip prefill over fully-cached pages. At least the prompt's
            # FINAL token always re-prefills: its logits sample the first
            # output token (a 100%-cached prompt therefore re-enters its
            # last shared page, which is the copy-on-write path).
            skip = min(len(hits) * self.page, len(r.prompt) - 1)
            self.cache_hits += len(hits)
            self.cache_misses += len(keys) - len(hits)
            self._m.hits.inc(len(hits))
            self._m.misses.inc(len(keys) - len(hits))
            r.cache_keys = keys
            r.cached_tokens = skip
            r.pos = skip
            self._lens[slot] = skip
            r.slot = slot
            r.status = RequestStatus.RUNNING
            r.admit_seq = self._admit_seq
            self._admit_seq += 1
            self._slots[slot] = r

    def _release(self, slot, status=None, error=None):
        """Free the slot's pages through the refcounts; ``status`` None is
        the requeue path (preemption — the request is NOT finalized), any
        terminal status finalizes the request."""
        r = self._slots[slot]
        for p in self._slot_tables[slot, :int(self._n_alloc[slot])]:
            self._unref_page(int(p))
        self._slots[slot] = None
        self._lens[slot] = 0
        self._n_alloc[slot] = 0
        if status is not None:
            self._finalize(r, status, error=error)

    def _preempt_youngest(self, excluding):
        """Free the youngest slot's pages, requeueing it for recompute
        (prompt := prompt + generated so far). Returns True if one was
        preempted."""
        victims = [(r.admit_seq, s) for s, r in enumerate(self._slots)
                   if r is not None and s != excluding]
        if not victims:
            return False
        _, slot = max(victims)
        r = self._slots[slot]
        # recompute prompt = ORIGINAL prompt + everything generated so far —
        # folding the current (possibly already-folded) prompt would
        # duplicate earlier output on a second preemption
        r.prompt = r.prompt0 + r.out
        self._release(slot, status=None)
        r.slot = None
        r.status = RequestStatus.QUEUED
        self._waiting.appendleft(r)
        self.preemptions += 1
        self._m.preempt.inc()
        return True

    def _ensure_page(self, slot, ahead=1):
        """Grow slot's page table to cover `ahead` more tokens; preempt the
        youngest other slot if the pool is dry."""
        needed = (int(self._lens[slot]) + ahead + self.page - 1) // self.page
        while int(self._n_alloc[slot]) < needed:
            p = self._alloc_page()
            if p is None:
                if not self._preempt_youngest(excluding=slot):
                    raise RuntimeError(
                        "page pool exhausted with a single slot — engine "
                        "misconfigured (max_len vs page pool)")
                continue
            na = int(self._n_alloc[slot])
            self._slot_tables[slot, na] = p
            self._slot_tables[slot, na + 1:] = p
            self._n_alloc[slot] = na + 1

    def _next_seed(self, r):
        if r.seed is not None:
            return int(r.seed)       # fixed seed: matches model.generate
        self._seed_counter += 1
        return int(self._seed_counter % (2 ** 31 - 1))

    def _emit(self, slot, token):
        """Record one generated token; release the slot when finished."""
        r = self._slots[slot]
        r.out.append(int(token))
        self._m.tokens.inc()
        if r.ttft is None:
            r.ttft = time.perf_counter() - r.t_submit
            self._m.ttft.observe(r.ttft)
        hit_eos = (r.eos is not None and r.out[-1] == r.eos)
        if (len(r.out) >= r.max_new or hit_eos
                or int(self._lens[slot]) >= self.max_len):
            self._release(slot, RequestStatus.EOS if hit_eos
                          else RequestStatus.FINISHED)

    def _prefill_chunk(self, slot):
        r = self._slots[slot]
        self._step_phase = ("prefill", (slot,))
        if _faults.active:
            _faults.raise_if("serving.step", rids=[r.rid], phase="prefill")
        start = r.pos
        n = min(self.chunk, len(r.prompt) - start)
        if self.prefix_cache:
            # about to write [start, start+n): un-share any page another
            # slot still maps (a fully-cached prompt re-prefilling its
            # final token into the last shared page lands here)
            self._cow_unshare(slot, start, n)
        toks = np.zeros((self.chunk,), np.int32)
        toks[:n] = r.prompt[start:start + n]
        finishes = (start + n) == len(r.prompt)
        r.prefill_dispatches += 1
        self.prefill_dispatches += 1
        self._m.prefill.inc()
        with _obs.trace_span("serving.prefill"):
            nxt, self.cache = self._prefill(
                self.W, self.cache, jnp.asarray(toks),
                jnp.asarray(np.int32(start)),
                jnp.asarray(self._slot_tables[slot]),
                jnp.asarray(np.int32(n)),
                jnp.asarray(np.int32(0 if r.do_sample else 1)),
                jnp.asarray(np.float32(r.temperature)),
                jnp.asarray(np.float32(r.top_p)),
                jnp.asarray(np.int32(r.top_k)),
                jnp.asarray(np.int32(self._next_seed(r))))
        r.pos += n
        self._lens[slot] = start + n
        if self.prefix_cache:
            self._register_pages(slot, r)
        if finishes:
            self._emit(slot, int(np.asarray(nxt)))

    def step(self):
        """One engine dispatch: a prefill chunk if any slot is mid-prompt,
        else one decode token for every active slot. Returns #slots served.

        This is the failure-isolation boundary: a step that raises never
        kills the engine.  Transient errors (``err.transient`` truthy) are
        retried with backoff; anything else triggers a quarantine sweep —
        the failing dispatch is re-run one slot at a time and the slot that
        still fails alone is finalized FAILED (pages freed), the rest keep
        serving.  Isolation is exact for host-side failures; a fault inside
        an already-dispatched XLA program is best-effort (the donated cache
        buffer may be unrecoverable) — the engine still degrades per-request
        instead of crashing the loop."""
        if self._any_deadline:
            self._expire_deadlines()
        self._step_phase = ("admit", ())
        try:
            served = self._step_impl()
        except Exception as e:  # noqa: BLE001 — the isolation boundary
            served = self._survive_step_failure(e)
        if self.debug_refcount_audit:
            problems = self.audit_refcounts()
            if problems:
                raise RuntimeError("page-refcount audit failed:\n  "
                                   + "\n  ".join(problems))
        return served

    def _step_impl(self):
        self._admit()
        if _obs.enabled():
            self._refresh_gauges()
        if _faults.active:
            point = _faults.fire("serving.slow_step")
            if point is not None and point.delay:
                time.sleep(point.delay)
        for slot, r in enumerate(self._slots):
            if r is not None and r.pos < len(r.prompt):
                self._prefill_chunk(slot)
                return 1
        live = [(s, r) for s, r in enumerate(self._slots) if r is not None]
        if not live:
            return 0
        if self._spec is not None:
            props = self._propose_drafts(live)
            if any(props.values()):
                return self._spec_step(live, props)
            # no slot has a draft this step: the plain decode block below
            # amortizes dispatch cost better than a 1-row verify would
        # block size: largest power of two <= every slot's remaining budget,
        # capped by decode_block (or the RTT-adapted target in auto mode);
        # any eos request needs per-token host inspection -> 1
        cap = self._block_target if self._auto_block else self.decode_block
        k = min(cap, min(r.max_new - len(r.out) for _, r in live))
        if any(r.eos is not None for _, r in live):
            k = 1
        k = 1 << max(0, k.bit_length() - 1)              # floor to pow2
        active = np.zeros((self.max_batch,), np.int32)
        tokens = np.zeros((self.max_batch,), np.int32)
        greedy = np.ones((self.max_batch,), np.int32)
        temp = np.ones((self.max_batch,), np.float32)
        topp = np.ones((self.max_batch,), np.float32)
        topk = np.zeros((self.max_batch,), np.int32)
        seeds = np.zeros((self.max_batch,), np.int32)
        fold = np.zeros((self.max_batch,), np.int32)
        for slot, r in live:
            if self._slots[slot] is not r:
                continue        # preempted by an earlier slot's growth
            self._ensure_page(slot, ahead=k)
        # growth may have preempted members of `live` — drop them before
        # building the batch (a stale entry would re-allocate pages to an
        # empty slot and decode a request that is back in the queue)
        live = [(s, r) for s, r in live if self._slots[s] is r]
        if not live:
            return 0
        for slot, r in live:
            active[slot] = 1
            tokens[slot] = r.out[-1]
            greedy[slot] = 0 if r.do_sample else 1
            temp[slot] = r.temperature
            topp[slot] = r.top_p
            topk[slot] = r.top_k
            seeds[slot] = self._next_seed(r)
            fold[slot] = 1 if r.seed is None else 0
        self._step_phase = ("decode", tuple(s for s, _ in live))
        if _faults.active:
            _faults.raise_if("serving.step", rids=[r.rid for _, r in live],
                             phase="decode")
        prog = self._decode_programs.get(k)
        compile_call = prog is None
        if compile_call:
            prog = self._decode_programs[k] = self._build_decode(k)
        self._m.decode.inc()
        t0 = time.perf_counter()
        with _obs.trace_span("serving.decode"):
            toks, self.cache = prog(
                self.W, self.cache, jnp.asarray(tokens),
                jnp.asarray(self._lens), jnp.asarray(self._slot_tables),
                jnp.asarray(active), jnp.asarray(greedy), jnp.asarray(temp),
                jnp.asarray(topp), jnp.asarray(topk), jnp.asarray(seeds),
                jnp.asarray(fold))
            toks = np.asarray(toks)                      # [k, B]
        dt = time.perf_counter() - t0
        if self._auto_block and not compile_call:
            # host sync above makes the wall time a true dispatch sample
            self._record_block_sample(k, dt)
        if not compile_call and _obs.enabled():
            # dispatch served k tokens for each live slot; exclude the
            # compile call so the histogram reflects steady-state latency
            for _ in live:
                self._m.token_latency.observe(dt / k)
        for j in range(k):
            for slot, r in live:
                if self._slots[slot] is not r:           # released mid-block
                    continue
                self._lens[slot] += 1
                self._emit(slot, int(toks[j, slot]))
        return len(live)

    # ----------------------------------------------------- failure isolation
    def _survive_step_failure(self, e):
        """Handle an exception that escaped :meth:`_step_impl`.  Transient
        errors re-dispatch through the shared backoff policy; everything
        else is attributed to a request and quarantined.  Returns the #slots
        the recovery path ended up serving."""
        phase, slots = self._step_phase
        if phase == "admit":
            # failed outside any dispatch — host-side bookkeeping, an
            # engine bug rather than a poison request: surface it
            raise e
        self.step_failures += 1
        self._m.step_fail[phase].inc()
        if getattr(e, "transient", False):
            ok, served, e = self._retry_step()
            if ok:
                return served
            phase, slots = self._step_phase   # the failing retry's phase
            if phase == "admit":
                raise e
        return self._isolate(phase, slots, e)

    def _retry_step(self):
        """Re-dispatch through the shared backoff policy.  Returns ``(True,
        served, None)`` when a retry lands, ``(False, 0, err)`` when the
        attempts run out — or a NON-transient error interrupts the retry
        run; either way isolation takes over from whatever phase the final
        error left in ``_step_phase``."""
        def attempt():
            try:
                return self._step_impl()
            except Exception as err:
                if getattr(err, "transient", False):
                    raise _TransientStep(err) from err
                raise

        def note(n, err, delay):
            self.step_retries += 1

        self.step_retries += 1        # the re-dispatch itself is a retry
        try:
            served = retry_call(attempt, policy=self._step_retry,
                                retry_on=(_TransientStep,),
                                op="serving.step", on_retry=note)
        except RetryError as err:
            return False, 0, err.__cause__.err
        except Exception as err:  # noqa: BLE001 — non-transient mid-retry
            return False, 0, err
        return True, served, None

    def _isolate(self, phase, slots, e):
        """Quarantine the poison request(s) behind a failed dispatch: a
        single-slot failure (prefill, or a 1-wide batch) is attributed
        directly; a batched decode/verify failure is bisected by re-running
        every member slot as a one-slot decode probe and quarantining
        exactly those that still fail alone."""
        todo = [s for s in slots if self._slots[s] is not None]
        if len(todo) <= 1:
            for s in todo:
                self._quarantine(s, e)
            return 0
        served = 0
        for s in todo:
            if self._slots[s] is None:
                continue          # released/preempted by an earlier probe
            self.quarantine_probes += 1
            self._m.probes.inc()
            try:
                self._decode_probe(s)
                served += 1
            except Exception as pe:  # noqa: BLE001 — probe attributes blame
                self._quarantine(s, pe)
        return served

    def _quarantine(self, slot, err):
        """Finalize the slot's request FAILED — the error is recorded on the
        request, its pages return through the refcounts (shared prefix-cache
        pages other slots map stay live) — and keep serving everyone else."""
        self._release(slot, RequestStatus.FAILED, error=err)

    def _decode_probe(self, slot):
        """One-slot k=1 decode dispatch — the isolation probe run for each
        member of a failed batch.  A raise here pins the failure on this
        slot; success emits the token the probe decoded anyway, so a
        surviving request loses no work to the sweep."""
        r = self._slots[slot]
        self._step_phase = ("decode", (slot,))
        if _faults.active:
            _faults.raise_if("serving.step", rids=[r.rid], phase="decode")
        self._ensure_page(slot, ahead=1)
        if self._slots[slot] is not r:
            return                # growth preempted the probe target
        active = np.zeros((self.max_batch,), np.int32)
        tokens = np.zeros((self.max_batch,), np.int32)
        greedy = np.ones((self.max_batch,), np.int32)
        temp = np.ones((self.max_batch,), np.float32)
        topp = np.ones((self.max_batch,), np.float32)
        topk = np.zeros((self.max_batch,), np.int32)
        seeds = np.zeros((self.max_batch,), np.int32)
        fold = np.zeros((self.max_batch,), np.int32)
        active[slot] = 1
        tokens[slot] = r.out[-1]
        greedy[slot] = 0 if r.do_sample else 1
        temp[slot] = r.temperature
        topp[slot] = r.top_p
        topk[slot] = r.top_k
        seeds[slot] = self._next_seed(r)
        fold[slot] = 1 if r.seed is None else 0
        prog = self._decode_programs.get(1)
        if prog is None:
            prog = self._decode_programs[1] = self._build_decode(1)
        self._m.decode.inc()
        with _obs.trace_span("serving.decode_probe"):
            toks, self.cache = prog(
                self.W, self.cache, jnp.asarray(tokens),
                jnp.asarray(self._lens), jnp.asarray(self._slot_tables),
                jnp.asarray(active), jnp.asarray(greedy), jnp.asarray(temp),
                jnp.asarray(topp), jnp.asarray(topk), jnp.asarray(seeds),
                jnp.asarray(fold))
            toks = np.asarray(toks)
        self._lens[slot] += 1
        self._emit(slot, int(toks[0, slot]))

    def audit_refcounts(self):
        """Cross-check every page-accounting structure against the others;
        returns a list of problem strings (empty means clean).  Invariants:
        each page's refcount equals its slot-table references; free and
        LRU-parked pages carry refcount 0 and never overlap; no page leaks
        (refcount 0 yet neither free nor parked); LRU pages are
        content-registered; the prefix key index is symmetric.  O(pages +
        slots·pages_per_slot); runs after every step under
        ``debug_refcount_audit``."""
        problems = []
        expected = np.zeros(self.n_pages, np.int64)
        for slot, r in enumerate(self._slots):
            if r is None:
                continue
            for j in range(int(self._n_alloc[slot])):
                expected[int(self._slot_tables[slot, j])] += 1
        free = [int(p) for p in self._free_pages]
        free_set = set(free)
        if len(free_set) != len(free):
            problems.append("free list holds duplicate pages")
        lru_set = {int(p) for p in self._lru}
        both = free_set & lru_set
        if both:
            problems.append(f"pages both free and LRU-parked: {sorted(both)}")
        for p in range(self.n_pages - 1):            # trash page excluded
            refs, exp = int(self._page_ref[p]), int(expected[p])
            if refs != exp:
                problems.append(f"page {p}: refcount {refs} != "
                                f"{exp} slot-table references")
            if refs == 0 and p not in free_set and p not in lru_set:
                problems.append(f"page {p}: leaked "
                                "(refcount 0, neither free nor LRU-parked)")
            if refs > 0 and (p in free_set or p in lru_set):
                problems.append(f"page {p}: referenced but on the "
                                "free/LRU list")
        for p in lru_set:
            if p not in self._page_key:
                problems.append(f"page {p}: LRU-parked but not "
                                "content-registered")
        for p, key in self._page_key.items():
            if self._key_page.get(key) != p:
                problems.append(f"page {p}: page->key->page asymmetric")
        for key, p in self._key_page.items():
            if self._page_key.get(p) != key:
                problems.append(f"page {p}: key->page->key asymmetric")
        return problems

    # ---------------------------------------------------- speculative decode
    def _propose_drafts(self, live):
        """Draft continuation tokens per live slot, capped so that drafts+1
        emitted tokens can neither exceed the request's remaining budget nor
        run past max_len."""
        props = {}
        target = self._spec_draft_target()
        for slot, r in live:
            cap = min(target, r.max_new - len(r.out) - 1,
                      self.max_len - int(self._lens[slot]) - 1)
            if cap < 1:
                props[slot] = []
                continue
            # full token history (prompt0+out survives preemption re-folds)
            props[slot] = self._proposer.propose(r.prompt0 + r.out, cap)[:cap]
        return props

    def _spec_step(self, live, props):
        """One speculative step: verify every live slot's pending token plus
        its drafts in a single multi-query dispatch, emit the accepted run,
        roll rejected pages back. Slots without a proposal ride along with
        one row (their pending token advances normally)."""
        for slot, r in live:
            if self._slots[slot] is not r:
                continue        # preempted by an earlier slot's growth
            self._ensure_page(slot, ahead=len(props.get(slot, ())) + 1)
        live = [(s, r) for s, r in live if self._slots[s] is r]
        if not live:
            return 0
        Kv = _ceil_pow2(max(len(props.get(s, ())) + 1 for s, _ in live))
        tokens = np.zeros((self.max_batch, Kv), np.int32)
        n_rows = np.zeros((self.max_batch,), np.int32)
        greedy = np.ones((self.max_batch,), np.int32)
        temp = np.ones((self.max_batch,), np.float32)
        topp = np.ones((self.max_batch,), np.float32)
        topk = np.zeros((self.max_batch,), np.int32)
        seeds = np.zeros((self.max_batch,), np.int32)
        fold = np.zeros((self.max_batch,), np.int32)
        for slot, r in live:
            drafts = props.get(slot, [])
            n_rows[slot] = 1 + len(drafts)
            tokens[slot, 0] = r.out[-1]
            tokens[slot, 1:1 + len(drafts)] = drafts
            greedy[slot] = 0 if r.do_sample else 1
            temp[slot] = r.temperature
            topp[slot] = r.top_p
            topk[slot] = r.top_k
            seeds[slot] = self._next_seed(r)
            fold[slot] = 1 if r.seed is None else 0
        self._step_phase = ("verify", tuple(s for s, _ in live))
        if _faults.active:
            _faults.raise_if("serving.step", rids=[r.rid for _, r in live],
                             phase="verify")
        prog = self._verify_programs.get(Kv)
        compile_call = prog is None
        if compile_call:
            prog = self._verify_programs[Kv] = self._build_verify(Kv)
        self.spec_dispatches += 1
        self._m.verify.inc()
        t0 = time.perf_counter()
        with _obs.trace_span("serving.verify"):
            toks, self.cache = prog(
                self.W, self.cache, jnp.asarray(tokens),
                jnp.asarray(self._lens), jnp.asarray(self._slot_tables),
                jnp.asarray(n_rows), jnp.asarray(greedy), jnp.asarray(temp),
                jnp.asarray(topp), jnp.asarray(topk), jnp.asarray(seeds),
                jnp.asarray(fold))
            toks = np.asarray(toks)                      # [B, Kv]
        dt = time.perf_counter() - t0
        if self._spec.adaptive and not compile_call:
            self._record_verify_sample(Kv, dt)
        proposed = accepted = 0
        for slot, r in live:
            drafts = props.get(slot, [])
            n = len(drafts)
            t = toks[slot]
            # accept the longest draft prefix the target would have sampled
            # itself: draft j+1 (fed at row j+1) survives iff it equals the
            # token sampled from row j's logits
            a = 0
            while a < n and drafts[a] == int(t[a]):
                a += 1
            proposed += n
            accepted += a
            m = a + 1                                    # tokens to emit
            for j in range(m):
                if self._slots[slot] is not r:
                    break        # eos / max_new released the slot mid-run
                self._lens[slot] += 1
                self._emit(slot, int(t[j]))
                self.spec_emitted += 1
            if self._slots[slot] is r:
                # roll back KV pages provisioned for rejected drafts
                self._truncate_pages(slot)
            if not compile_call and _obs.enabled():
                self._m.token_latency.observe(dt / m)
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self._m.spec_proposed.inc(proposed)
        self._m.spec_accepted.inc(accepted)
        if proposed:
            ratio = accepted / proposed
            self._m.spec_acceptance.observe(ratio)
            self._spec_accept_ema = (
                ratio if self._spec_accept_ema is None
                else 0.9 * self._spec_accept_ema + 0.1 * ratio)
        return len(live)

    def _truncate_pages(self, slot):
        """Free pages past ceil(lens/page) back to the pool — the rollback
        half of speculative decoding. Safe by construction: pages past the
        prompt are always privately allocated (refcount 1) and never
        registered in the prefix index, so a partially-filled page is
        truncated, never shared; the stale KV beyond lens is unreachable
        because attention masks by context length."""
        lens = int(self._lens[slot])
        needed = max(1, (lens + self.page - 1) // self.page)
        na = int(self._n_alloc[slot])
        if na <= needed:
            return
        for j in range(needed, na):
            self._unref_page(int(self._slot_tables[slot, j]))
        self._slot_tables[slot, needed:] = self._slot_tables[slot, needed - 1]
        self._n_alloc[slot] = needed

    def _record_verify_sample(self, rows, wall_dt):
        samples = self._spec_samples.setdefault(rows, [])
        samples.append(wall_dt)
        del samples[:-8]

    def _spec_draft_target(self):
        """Draft length maximizing expected emitted tokens per second,
        E(k) / t(rows(k)), from the verify step's OWN cost fit (decode
        blocks consume exactly k tokens; a verify step consumes a variable
        1..k+1, so it gets a separate t(rows) = RTT + rows*c model) and the
        acceptance-rate EMA: E(k) = 1 + a + a^2 + ... + a^k."""
        cfg = self._spec
        if not cfg.adaptive:
            return cfg.max_draft
        sampled = {kk: sorted(v)[len(v) // 2]
                   for kk, v in self._spec_samples.items() if v}
        if len(sampled) < 2:
            return cfg.max_draft      # not solvable yet: be optimistic
        ks = sorted(sampled)
        c, rtt = np.polyfit(np.asarray(ks, np.float64),
                            np.asarray([sampled[kk] for kk in ks],
                                       np.float64), 1)
        if c <= 0 or rtt < 0:
            return cfg.max_draft
        alpha = min(0.99, max(0.0, self._spec_accept_ema
                              if self._spec_accept_ema is not None else 0.5))
        best_k, best_rate = 1, -1.0
        for k in range(1, cfg.max_draft + 1):
            e = (k + 1 if alpha == 1.0
                 else (1 - alpha ** (k + 1)) / (1 - alpha))
            rate = e / (rtt + _ceil_pow2(k + 1) * c)
            if rate > best_rate:
                best_rate, best_k = rate, k
        return best_k

    def spec_stats(self):
        """Always-on speculative-decoding counters (zero when the
        ``spec_decode`` knob is off). ``tokens_per_step`` is tokens emitted
        per VERIFY dispatch — the speculative speedup factor (> 1.0 means
        drafts are being accepted); the registry mirrors proposed/accepted
        as ``serving_spec_*_total`` plus the acceptance histogram."""
        return {
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "emitted": self.spec_emitted,
            "verify_dispatches": self.spec_dispatches,
            "acceptance_rate": (self.spec_accepted / self.spec_proposed
                                if self.spec_proposed else 0.0),
            "tokens_per_step": (self.spec_emitted / self.spec_dispatches
                                if self.spec_dispatches else 0.0),
            "draft_target": (self._spec_draft_target()
                             if self._spec is not None else 0),
        }

    def _record_block_sample(self, k, wall_dt):
        """Auto decode-block: least-squares fit of t(k) = RTT + k*c over
        the per-size medians of EVERY sampled block size, targeting the
        power-of-two k where per-dispatch constant costs <= ~25% of device
        time (k >= 3*RTT/c). Fitting all sizes (instead of the two
        earliest medians) lets late samples at large k keep correcting the
        model, and every 64th sample the target drops back to a small k
        for one dispatch so the intercept estimate can't go stale."""
        samples = self._block_samples.setdefault(k, [])
        samples.append(wall_dt)
        del samples[:-8]
        self._block_n += 1
        sampled = {kk: sorted(v)[len(v) // 2]
                   for kk, v in self._block_samples.items() if v}
        if len(sampled) < 2:
            # force a second sample size next step so the model is solvable
            self._block_target = min(2, self.decode_block) \
                if 1 in sampled else 1
            return
        ks = sorted(sampled)
        c, rtt = np.polyfit(np.asarray(ks, np.float64),
                            np.asarray([sampled[kk] for kk in ks],
                                       np.float64), 1)
        if c <= 0 or rtt <= 0:       # noise/local runtime: RTT negligible
            self._block_target = min(2, self.decode_block)
            return
        want = max(1, int(3 * rtt / c))
        want = 1 << (want.bit_length() - 1)              # floor to pow2
        self._block_target = min(want, self.decode_block)
        if self._block_n % 64 == 0:
            # periodic small-k re-sample refreshes the RTT intercept
            self._block_target = min(2, self.decode_block)

    @property
    def auto_decode_block(self):
        """Current RTT-adapted block target (auto mode only)."""
        return self._block_target if self._auto_block else self.decode_block

    def run_until_done(self, max_steps=10000):
        steps = 0
        while (self._waiting or any(s is not None for s in self._slots)) \
                and steps < max_steps:
            self.step()
            steps += 1
        return steps

    def _refresh_gauges(self):
        """Mirror instantaneous engine state into the registry gauges."""
        n_active = sum(1 for s in self._slots if s is not None)
        self._m.queue_depth.set(len(self._waiting))
        self._m.active_slots.set(n_active)
        self._m.occupancy.set(n_active / self.max_batch)
        self._m.cached_pages.set(len(self._key_page))
        self._m.reclaimable.set(len(self._lru))
        self._m.free_pages.set(len(self._free_pages))

    def metrics(self):
        """This engine's telemetry series from the process-wide registry.

        Values accumulate only while ``paddle_tpu.observability.enable()``
        is on; :meth:`prefix_cache_stats` stays the always-on plain-dict
        view of the same counters."""
        if _obs.enabled():
            self._refresh_gauges()
        return _obs.snapshot(prefix="serving_",
                             labels={"engine": self._m.label})

    def prefix_cache_stats(self):
        """Counters for the automatic prefix cache (all zero when the
        `prefix_cache` knob is off).

        The same counters are exported through the observability registry
        (``serving_prefix_cache_events_total{engine=...}``); this dict is
        the always-on thin compatibility view."""
        return {
            "hits": self.cache_hits,
            "misses": self.cache_misses,
            "evictions": self.cache_evictions,
            "cow_copies": self.cache_cow_copies,
            "prefill_dispatches": self.prefill_dispatches,
            "cached_pages": len(self._key_page),
            "reclaimable_pages": len(self._lru),
        }

    def kv_bytes_per_page(self):
        """HBM bytes one KV page costs across all layers (both K and V,
        including int8 scales) — the unit of the page_pool budget."""
        return sum(int(a.nbytes) for a in self.cache) // self.n_pages

    def result(self, rid):
        return self._finished[rid].out

    def ttft(self, rid):
        """Seconds from add_request to the first generated token."""
        return self._finished[rid].ttft

    def _lookup(self, rid):
        """The live or terminal :class:`Request` for ``rid`` wherever it
        is — waiting, in a slot, or finished.  KeyError when unknown."""
        for r in self._waiting:
            if r.rid == rid:
                return r
        for r in self._slots:
            if r is not None and r.rid == rid:
                return r
        return self._finished[rid]

    def new_tokens(self, rid):
        """Incremental stream accessor: the tokens ``rid`` generated since
        the previous ``new_tokens(rid)`` call (empty list when none yet).
        Output is append-only across the whole lifecycle — preemption
        re-folds the *prompt*, never the emitted stream — so concatenating
        every batch reproduces :meth:`result` exactly.  This is the public
        surface the streaming gateway reads; it never touches slot state."""
        r = self._lookup(rid)
        toks = [int(t) for t in r.out[r.stream_pos:]]
        r.stream_pos += len(toks)
        return toks

    def stream(self, rid, max_steps=100000):
        """Generator driving the engine until ``rid`` is terminal, yielding
        its tokens one by one as they are emitted (other in-flight requests
        keep being served by the same steps).  Single-caller convenience —
        a multi-replica front door runs the step loop elsewhere and polls
        :meth:`new_tokens` instead."""
        steps = 0
        while True:
            yield from self.new_tokens(rid)
            if self._lookup(rid).status.terminal:
                return
            if steps >= max_steps:
                raise RuntimeError(f"stream({rid}) exceeded {max_steps} steps")
            self.step()
            steps += 1

    def fail_all(self, error):
        """Finalize EVERY live request (waiting and running) as FAILED with
        ``error`` recorded — the front door calls this when a replica's
        step loop dies, so inflight requests end with a typed terminal
        status instead of hanging their streams forever."""
        while self._waiting:
            self._finalize(self._waiting.popleft(), RequestStatus.FAILED,
                           error=error)
        for slot, r in enumerate(self._slots):
            if r is not None:
                self._release(slot, RequestStatus.FAILED, error=error)

    def status(self, rid):
        """The request's :class:`RequestStatus` wherever it lives — waiting,
        in a slot, or terminal.  KeyError for an unknown rid."""
        return self._lookup(rid).status

    def error(self, rid):
        """The recorded ``ExceptionType: message`` string for a FAILED
        request; None for every other terminal status."""
        return self._finished[rid].error

    def health(self):
        """One JSON-able liveness snapshot for external monitors — plain
        counters, available whether or not observability is enabled."""
        n_active = sum(1 for s in self._slots if s is not None)
        return {
            "active_slots": n_active,
            "max_batch": self.max_batch,
            "waiting": len(self._waiting),
            "finished": len(self._finished),
            "free_pages": len(self._free_pages),
            "reclaimable_pages": len(self._lru),
            "total_pages": self.n_pages - 1,
            "shed_requests": self.shed_requests,
            "timeouts": self.timeouts,
            "cancels": self.cancels,
            "quarantined": self.quarantined,
            "step_failures": self.step_failures,
            "step_retries": self.step_retries,
            "quarantine_probes": self.quarantine_probes,
            "preemptions": self.preemptions,
        }
