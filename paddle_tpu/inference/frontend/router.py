"""Prefix-cache-aware request routing across engine replicas.

The serving engine keys its radix-style prefix index by chain hashes of full
KV pages (:func:`paddle_tpu.inference.serving.prefix_page_keys`).  Because
the chain hash is deterministic and shared, the router can compute a
request's page keys *before* dispatch and ask: which replica already holds
the longest prefix of those pages?  Routing there turns the replica's cached
pages into skipped prefill work.

The router keeps a radix-style NODE index shared across replicas: because a
chain key already encodes its whole prefix (key_i hashes key_{i-1}), the
radix trie collapses to one dict ``chain key -> set of replicas holding that
node`` — the same collapse the engine applies to its own prefix index.  The
index is maintained from the engine's own cache events (``register`` when a
page enters the index, ``evict`` when the LRU reclaims it) —
:class:`~.replica.EngineReplica` subscribes the engine's
``cache_event_listener`` hook to :meth:`PrefixAffinityRouter.note_event`, so
the mirror can never drift from the real index except by the events in
flight during a step (self-correcting on the next event).

Scoring walks the request's chain ONCE, intersecting the per-node holder
sets — replicas drop out at the depth where their cache diverges, so the
walk is O(prompt pages) with early exit, independent of replica count
(the old per-replica probe loop re-walked the chain R times).  Scoring is
``(longest contiguous prefix-page overlap, -load, name)``: the deepest
cached prefix wins, load breaks overlap ties, and the replica name breaks
exact ties so routing is deterministic under equal state.  With zero
overlap everywhere the router degrades to least-loaded.
"""
from __future__ import annotations

import threading

from ... import observability as _obs
from ..serving import prefix_page_keys

__all__ = ["RouteDecision", "PrefixAffinityRouter", "RoundRobinRouter"]


class RouteDecision:
    """Outcome of one routing call: the chosen replica, why it won
    (``affinity`` | ``least_loaded`` | ``round_robin``), and how many
    contiguous prefix pages it already caches.  When load skew overrode
    affinity (``max_load_skew``), ``holder`` names the passed-over
    deepest-overlap replica and ``holder_overlap`` its depth — the peer
    KV-pull seam: the chosen replica can cold-pull the holder's pages."""

    __slots__ = ("replica", "reason", "overlap", "holder", "holder_overlap")

    def __init__(self, replica, reason, overlap=0, holder=None,
                 holder_overlap=0):
        self.replica = replica
        self.reason = reason
        self.overlap = int(overlap)
        self.holder = holder
        self.holder_overlap = int(holder_overlap)

    def __repr__(self):
        return (f"RouteDecision({getattr(self.replica, 'name', self.replica)!r},"
                f" {self.reason!r}, overlap={self.overlap})")


class PrefixAffinityRouter:
    """Route to the replica whose prefix cache holds the deepest prefix of
    the request; fall back to least-loaded.  Thread-safe: ``note_event``
    arrives from replica step threads while ``route`` runs on gateway
    threads."""

    def __init__(self, page_size, max_load_skew=None):
        """``max_load_skew``: load-balance override for affinity wins.  By
        default the deepest cached prefix always wins; with a skew bound,
        when the affinity winner's load exceeds the least-loaded replica's
        by MORE than ``max_load_skew``, the least-loaded replica is chosen
        instead and the affinity winner is exposed as
        :attr:`RouteDecision.holder` so the caller can cold-pull its pages
        (the peer KV tier)."""
        self.page = int(page_size)
        self.max_load_skew = max_load_skew
        self._lock = threading.Lock()
        # radix node index: a chain key names a whole prefix, so the trie
        # is one flat dict of nodes with the set of replicas holding each
        self._nodes = {}         # chain key -> set of replica names
        self._by_replica = {}    # replica name -> set of live chain keys

    # ---- index maintenance (driven by engine cache events) ------------------
    def note_event(self, replica_name, event, key):
        """Mirror one engine cache event into the node index.  ``register``
        adds the replica to the key's node, ``evict`` drops it; unknown
        events are ignored so the listener contract stays
        forward-compatible."""
        with self._lock:
            keys = self._by_replica.setdefault(replica_name, set())
            if event == "register":
                keys.add(key)
                self._nodes.setdefault(key, set()).add(replica_name)
            elif event == "evict":
                keys.discard(key)
                holders = self._nodes.get(key)
                if holders is not None:
                    holders.discard(replica_name)
                    if not holders:
                        del self._nodes[key]

    def forget(self, replica_name):
        """Drop a replica's whole index (its pages died with it)."""
        with self._lock:
            for key in self._by_replica.pop(replica_name, ()):
                holders = self._nodes.get(key)
                if holders is not None:
                    holders.discard(replica_name)
                    if not holders:
                        del self._nodes[key]

    def known_keys(self, replica_name):
        """Snapshot of the chain keys mirrored for one replica."""
        with self._lock:
            return frozenset(self._by_replica.get(replica_name, ()))

    # ---- scoring -------------------------------------------------------------
    def overlap(self, replica_name, chain_keys):
        """Longest *contiguous* prefix of ``chain_keys`` present in the
        replica's index.  Contiguity matters: chain key i is only reusable
        when pages 0..i-1 are too, exactly like the engine's admission walk."""
        with self._lock:
            n = 0
            for k in chain_keys:
                holders = self._nodes.get(k)
                if holders is None or replica_name not in holders:
                    break
                n += 1
            return n

    def _overlaps(self, chain_keys, names):
        """One walk down the request's chain: at each node, replicas not
        holding it drop out, and survivors' overlap deepens.  Early exit
        when nobody survives — O(prompt pages), not O(replicas × pages)."""
        overlaps = dict.fromkeys(names, 0)
        with self._lock:
            alive = set(names)
            for k in chain_keys:
                alive &= self._nodes.get(k, frozenset())
                if not alive:
                    break
                for name in alive:
                    overlaps[name] += 1
        return overlaps

    def route(self, prompt_ids, replicas):
        """Pick a replica for ``prompt_ids`` among ``replicas`` (objects with
        ``.name`` and ``.load()``).  Deterministic: equal (overlap, load)
        resolves by replica name."""
        if not replicas:
            raise ValueError("no replicas to route to")
        chain = prefix_page_keys(prompt_ids, self.page)
        overlaps = self._overlaps(chain, [r.name for r in replicas])
        loads = {r.name: r.load() for r in replicas}
        scored = sorted(
            ((-overlaps[r.name], loads[r.name], r.name, r) for r in replicas),
            key=lambda t: t[:3])
        neg_overlap, best_load, _, best = scored[0]
        if neg_overlap < 0:
            if self.max_load_skew is not None:
                coldest = min(replicas,
                              key=lambda r: (loads[r.name], r.name))
                if coldest is not best and \
                        best_load - loads[coldest.name] > self.max_load_skew:
                    # the cache holder is too hot: route to the coldest
                    # replica and expose the holder for a peer page pull
                    _obs.FRONTEND_AFFINITY.inc(event="skew_override")
                    return RouteDecision(
                        coldest, "least_loaded",
                        overlap=overlaps[coldest.name], holder=best,
                        holder_overlap=-neg_overlap)
            _obs.FRONTEND_AFFINITY.inc(event="hit")
            return RouteDecision(best, "affinity", overlap=-neg_overlap)
        _obs.FRONTEND_AFFINITY.inc(event="miss")
        return RouteDecision(best, "least_loaded", overlap=0)


class RoundRobinRouter:
    """Affinity-blind baseline: cycle through the replica list in order.
    Used by the bench comparison and as the control in the affinity tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._i = 0

    def note_event(self, replica_name, event, key):
        """Accepted and ignored — keeps the router interface uniform."""

    def forget(self, replica_name):
        """Accepted and ignored — keeps the router interface uniform."""

    def route(self, prompt_ids, replicas):
        if not replicas:
            raise ValueError("no replicas to route to")
        with self._lock:
            r = replicas[self._i % len(replicas)]
            self._i += 1
        return RouteDecision(r, "round_robin")
