"""Stdlib socket RPC for replica workers — length-prefixed pickle frames.

The fleet's control plane (``submit`` / ``poll`` / ``cancel`` / ``health``)
crosses process boundaries over this: one :class:`RpcServer` per worker
process, one :class:`RpcClient` per remote replica handle in the gateway.
Deliberately tiny — blocking sockets, a thread per server connection,
length-prefixed pickle frames — because the payloads are token lists and
status enums, not tensors (bulk KV traffic rides XLA collectives or the
disaggregation handoff, never arbitrary objects).

Frame format: ``u32 pickle_len | u32 n_buffers | pickle`` followed by
``n_buffers`` × ``u64 len | raw bytes``.  The pickle is protocol 5 with a
``buffer_callback``, so large contiguous buffers (the numpy page blocks of
a cross-host KV handoff, ``pull_pages``/``push_pages`` payloads) travel
OUT-OF-BAND: the in-band pickle stays a few hundred bytes of structure
while each buffer is sent straight from its memoryview with zero in-band
copy, and received into exactly-sized bytearrays that ``pickle.loads``
rehydrates in place.  Ordinary ops (ints, strings, small lists) produce
zero out-of-band buffers and behave exactly as before.

Both ends are the same codebase, so exceptions travel by pickle: a worker
raising :class:`~.admission.ShedError` re-raises as ``ShedError`` in the
gateway with ``reason`` / ``retry_after`` intact.  An exception that won't
pickle degrades to ``RuntimeError(repr)`` rather than poisoning the
connection.

Connection failures surface as :class:`RpcError` — the remote-replica layer
maps those to replica death.  Fault points ``rpc.send`` / ``rpc.recv``
(:mod:`paddle_tpu.testing.faults`, ctx has ``op``) fire client-side around
the request/response halves so chaos tests can sever a live worker's
channel without touching the process.

Request tracing rides the frame as a third element: a request is
``(op, kwargs, trace_ctx)`` where ``trace_ctx`` is
:func:`~paddle_tpu.observability.flight.wire_context`'s tiny
``(trace_id, lamport)`` tuple or None; a reply is
``(status, value, lamport)``.  The server adopts the sender's Lamport
stamp and installs the context ambiently around the handler, so worker-side
span events join the caller's trace with monotone causal ordering; the
client folds the reply stamp back in.  Both ends still accept bare
two-element frames from peers predating the ctx field.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading

from ...observability import flight as _flight
from ...testing import faults as _faults

__all__ = ["RpcError", "RpcServer", "RpcClient"]

_OK, _ERR = 0, 1


class RpcError(ConnectionError):
    """The RPC channel itself failed (connect, send, or recv) — distinct
    from an exception the remote handler raised, which re-raises as
    itself."""


def _encode_frame(obj):
    """Split ``obj`` into (in-band pickle, out-of-band buffer list) — the
    protocol-5 fast path.  Factored from the socket write so tests can
    assert bytes-on-the-wire without a socket."""
    bufs: list = []
    try:
        payload = pickle.dumps(obj, protocol=5, buffer_callback=bufs.append)
        return payload, [b.raw() for b in bufs]
    except BufferError:
        # a non-contiguous buffer cannot ship raw: fall back to in-band
        return pickle.dumps(obj, protocol=5), []


def _send_frame(sock, obj):
    payload, bufs = _encode_frame(obj)
    sock.sendall(struct.pack("!II", len(payload), len(bufs)) + payload)
    for raw in bufs:
        sock.sendall(struct.pack("!Q", raw.nbytes))
        sock.sendall(raw)             # memoryview: no in-band copy


def _recv_frame(sock):
    hdr = _recv_exact(sock, 8)
    n, nbufs = struct.unpack("!II", hdr)
    payload = _recv_exact(sock, n)
    bufs = []
    for _ in range(nbufs):
        (blen,) = struct.unpack("!Q", _recv_exact(sock, 8))
        bufs.append(_recv_exact(sock, blen))
    return pickle.loads(payload, buffers=bufs)


def _recv_exact(sock, n):
    buf = bytearray(n)
    view, got = memoryview(buf), 0
    while got < n:
        k = sock.recv_into(view[got:])
        if not k:
            raise RpcError("rpc connection closed")
        got += k
    return buf


class RpcServer:
    """Serve ``handler(op, kwargs)`` over TCP until :meth:`close`.

    Each accepted connection gets a daemon thread running request frames in
    a loop; :meth:`close` shuts the listener down and joins the accept
    thread (per-connection threads exit when their peer disconnects or the
    listener's close unblocks them).
    """

    def __init__(self, handler, host="127.0.0.1", port=0):
        self.handler = handler
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._srv.bind((host, port))
            self._srv.listen(128)
            self.host, self.port = host, self._srv.getsockname()[1]
        except OSError:
            # bind/listen failure (EADDRINUSE on a worker respawn) must not
            # leak the listener fd: the caller never gets a server to close
            self._srv.close()
            raise
        self._accept_thread = None
        self._closing = False
        self._conns = set()
        self._conns_lock = threading.Lock()

    def start(self):
        if self._accept_thread is None:
            self._accept_thread = threading.Thread(
                target=self._accept_loop, name=f"rpc-accept-{self.port}",
                daemon=True)
            self._accept_thread.start()
        return self

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # listener closed: shut down
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                if self._closing:
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             name=f"rpc-conn-{self.port}",
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                try:
                    frame = _recv_frame(conn)
                except (RpcError, OSError, EOFError, pickle.UnpicklingError):
                    return
                # (op, kw, trace_ctx) since the tracing plane; accept the
                # bare (op, kw) frame from peers predating the ctx field
                op, kw = frame[0], frame[1]
                ctx = _flight.adopt_wire(frame[2] if len(frame) > 2 else None)
                try:
                    with _flight.use_context(ctx):
                        reply = (_OK, self.handler(op, kw),
                                 _flight.wire_context())
                except BaseException as e:  # noqa: BLE001 — RPC boundary
                    try:
                        pickle.dumps(e)
                    except Exception:
                        e = RuntimeError(f"unpicklable remote error: {e!r}")
                    reply = (_ERR, e, None)
                try:
                    _send_frame(conn, reply)
                except OSError:
                    return
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)

    def close(self):
        with self._conns_lock:
            self._closing = True
            conns = list(self._conns)
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._srv.close()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            c.close()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=10.0)
            self._accept_thread = None


class RpcClient:
    """Call a worker's ops over a small pool of pooled connections.

    One socket per *concurrent* call (checked out of a free list, returned
    on success) so long-polling one stream never serializes another; a
    socket that errors is discarded, not reused.  All channel failures
    raise :class:`RpcError`; remote handler exceptions re-raise as
    themselves.
    """

    def __init__(self, host, port, connect_timeout=5.0, call_timeout=60.0):
        self.host, self.port = host, int(port)
        self.connect_timeout = float(connect_timeout)
        self.call_timeout = float(call_timeout)
        self._free = []
        self._lock = threading.Lock()
        self.closed = False

    def _checkout(self):
        with self._lock:
            if self.closed:
                raise RpcError("rpc client closed")
            if self._free:
                return self._free.pop()
        try:
            sock = socket.create_connection((self.host, self.port),
                                            timeout=self.connect_timeout)
        except OSError as e:
            raise RpcError(
                f"cannot reach worker at {self.host}:{self.port}: {e}") from e
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            sock.close()                # a leaked fd per failed checkout
            raise                       # starves the pool under retry loops
        return sock

    def _checkin(self, sock):
        with self._lock:
            if not self.closed and len(self._free) < 8:
                self._free.append(sock)
                return
        sock.close()

    def call(self, op, deadline=None, ctx=None, **kw):
        """One round trip: returns the handler's value or re-raises its
        exception.  ``deadline`` bounds the whole call socket-side (the
        server adds no deadline of its own); it is a separate parameter so
        ops are free to take a ``timeout`` kwarg of their own.  ``ctx`` is
        the trace context to thread through the frame — pass
        :func:`~paddle_tpu.observability.flight.wire_context`'s tuple for a
        request-scoped call, or an explicit None for control-plane traffic
        (graftlint AT103 flags call sites that silently drop it)."""
        sock = self._checkout()
        try:
            sock.settimeout(self.call_timeout if deadline is None
                            else float(deadline))
            _faults.FAULTS.maybe_fire("rpc.send", op=op)
            try:
                _send_frame(sock, (op, kw, ctx))
            except OSError as e:
                raise RpcError(f"rpc send failed ({op}): {e}") from e
            _faults.FAULTS.maybe_fire("rpc.recv", op=op)
            try:
                reply = _recv_frame(sock)
            except (OSError, EOFError, pickle.UnpicklingError) as e:
                raise RpcError(f"rpc recv failed ({op}): {e}") from e
        except BaseException:
            sock.close()
            raise
        self._checkin(sock)
        status, value = reply[0], reply[1]
        if len(reply) > 2 and reply[2] is not None:
            _flight.adopt_wire(reply[2])   # fold the server's clock back in
        if status == _ERR:
            raise value
        return value

    def close(self):
        with self._lock:
            self.closed = True
            free, self._free = self._free, []
        for s in free:
            s.close()
