"""Durable request plane: a write-ahead request journal + the table that
re-drives it onto a :class:`~.replica.ReplicaSet`.

The gateway's durability gap (PR 14 closed the *replica* half) is the
gateway process itself: an accepted request lived only in handler-thread
state, so a gateway crash lost it and a client disconnect cancelled it.
This module makes acceptance durable:

- :class:`RequestJournal` — append-only JSONL segments on local disk.
  Every record is one JSON object carrying a CRC32 of its own payload, so
  a torn tail (crash mid-write) is detected and skipped on replay rather
  than poisoning it.  Appends go to the newest segment only; a reopened
  journal NEVER appends to a pre-existing segment (its tail may be torn) —
  it starts a fresh one.  Critical records (ACCEPTED, TERMINAL, and the
  rotation/compaction boundaries) are fsynced before the append returns;
  token batches ride the cheaper flush-only path by default
  (``fsync="always"`` upgrades them).  Segments rotate at a byte bound and
  terminal requests are periodically *compacted*: their
  ``ACCEPTED → TOKENS×N → TERMINAL`` record chains fold into single
  ``RESULT`` records (idempotency replay stays answerable) written via the
  atomic tmp + ``os.replace`` (+ dir fsync) idiom, and old segments are
  deleted.

- :class:`DurableRequest` — the in-memory face of one journaled request:
  its token list, terminal status, and a condition that SSE writers wait
  on.  ``events(after=seq)`` yields ``(seq, token)`` pairs from any
  offset, which is what ``Last-Event-ID`` reattach rides on.

- :class:`DurableRequestPlane` — the keyed table tying journal to fleet.
  ``submit`` journals ACCEPTED (fsynced) *before* returning — "accepted"
  means "on disk" — then a per-request pump thread drains the replica
  stream, journaling each token batch BEFORE publishing it to clients.
  That order is the reattach invariant: the journal is always ≥ any
  client's view, so a reconnect replayed from the journal can never have
  a gap against what the client already saw.  ``recover()`` replays the
  journal on a restarted gateway: terminal requests become replay-only
  entries (idempotent re-submits are served from them without touching
  the fleet), non-terminal ones are re-driven through the engine's
  ``resume_tokens`` re-prefill machinery — greedy/fixed-seed streams
  continue byte-identical.  Detached streams (client vanished pre-
  terminal) are cancelled only after a grace TTL, giving the client a
  reconnect window instead of the old insta-cancel.

Fault points: ``journal.append`` (record append fails; ctx ``kind``),
``journal.fsync`` (the critical-path fsync raises), ``gateway.recover``
(re-driving one journaled request fails during recovery; ctx ``key``).
"""
from __future__ import annotations

import json
import os
import threading
import time
import zlib

from ... import observability as _obs
from ...testing import faults as _faults
from ..serving import RequestStatus as _RequestStatus
from .admission import ShedError
from .replica import ReplicaDeadError

__all__ = ["JournalCorruption", "RequestJournal", "DurableRequest",
           "DurableRequestPlane"]

_SEG_PREFIX = "seg-"
_SEG_SUFFIX = ".jsonl"

# record kinds (the "k" field): one letter keeps token-batch records small
_ACCEPTED = "A"
_TOKENS = "T"
_TERMINAL = "F"
_RESULT = "R"        # compacted terminal request (ACCEPTED+TOKENS+TERMINAL)
_KIND_NAMES = {_ACCEPTED: "accepted", _TOKENS: "tokens",
               _TERMINAL: "terminal", _RESULT: "result"}


class JournalCorruption(RuntimeError):
    """A record failed its CRC or parse — surfaced only by strict replays;
    the normal recovery path counts and skips instead."""


def _encode(payload):
    """One journal line: the payload JSON plus a CRC32 of that exact
    serialization under ``"c"``.  Key order is pinned so the CRC is a pure
    function of the payload."""
    body = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    crc = zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF
    return ("{\"c\":%d,%s" % (crc, body[1:])).encode("utf-8") + b"\n"


def _decode(line):
    """Parse + CRC-check one line; returns the payload dict or raises
    :class:`JournalCorruption` (torn tail, bitrot, partial write)."""
    try:
        rec = json.loads(line.decode("utf-8"))
        crc = rec.pop("c")
    except (ValueError, KeyError, UnicodeDecodeError) as e:
        raise JournalCorruption(f"unparseable record: {e}") from e
    body = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    if zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF != crc:
        raise JournalCorruption("record CRC mismatch")
    return rec


class _Replayed:
    """Accumulated replay state of one request key."""

    __slots__ = ("prompt", "kw", "tokens", "status", "error")

    def __init__(self):
        self.prompt = None
        self.kw = {}
        self.tokens = []
        self.status = None       # RequestStatus once a TERMINAL/RESULT lands
        self.error = None


class RequestJournal:
    """Append-only CRC'd JSONL write-ahead journal over segment files in
    one directory.  All methods are thread-safe (one internal lock — the
    plane's pump threads and submit path share it).

    ``fsync`` policy: ``"critical"`` (default) fsyncs ACCEPTED/TERMINAL
    appends and rotation/compaction boundaries; ``"always"`` additionally
    fsyncs every token batch; ``"never"`` trusts the page cache (tests).
    """

    def __init__(self, path, segment_bytes=1 << 20, fsync="critical",
                 keep_terminal=512):
        if fsync not in ("always", "critical", "never"):
            raise ValueError(f"unknown fsync policy {fsync!r}")
        self.path = str(path)
        self.segment_bytes = int(segment_bytes)
        self.fsync = fsync
        self.keep_terminal = int(keep_terminal)
        self._mu = threading.RLock()
        self._fh = None
        self._seg_index = 0
        self.appended = 0           # records appended by this instance
        os.makedirs(self.path, exist_ok=True)
        existing = self._segment_indices()
        # never append to a pre-existing segment: its tail may be torn from
        # the crash that brought us here — replay tolerates the tear, an
        # append after it would not
        self._seg_index = (existing[-1] + 1) if existing else 0
        self._open_segment()

    # ---- segment plumbing ----------------------------------------------------
    def _seg_path(self, index):
        return os.path.join(self.path, f"{_SEG_PREFIX}{index:08d}{_SEG_SUFFIX}")

    def _segment_indices(self):
        out = []
        for name in os.listdir(self.path):
            if name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX):
                try:
                    out.append(int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]))
                except ValueError:
                    continue
        return sorted(out)

    def _open_segment(self):
        # "ab" (not "w"): the segment index is fresh so the file is new, and
        # append mode can never truncate a journal on a racing reopen
        self._fh = open(self._seg_path(self._seg_index), "ab")

    def _fsync_fh(self):
        _faults.FAULTS.maybe_fire("journal.fsync")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def _fsync_dir(self):
        fd = os.open(self.path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _rotate(self):
        """Seal the active segment (fsynced) and start the next one."""
        self._fsync_fh()
        self._fh.close()
        self._seg_index += 1
        self._open_segment()
        self._fsync_dir()

    # ---- append --------------------------------------------------------------
    def _append(self, payload, critical):
        t0 = time.perf_counter()
        kind = payload["k"]
        _faults.FAULTS.maybe_fire("journal.append", kind=_KIND_NAMES[kind])
        with self._mu:
            if self._fh is None:
                raise RuntimeError("journal is closed")
            self._fh.write(_encode(payload))
            if critical and self.fsync != "never" or self.fsync == "always":
                # fsync under _mu BY DESIGN: the durability contract is
                # fsync-before-ack, so the record must be on disk before
                # any later append (or ack) can be ordered after it
                self._fsync_fh()  # graftlint: disable=concurrency
            else:
                self._fh.flush()
            self.appended += 1
            if self._fh.tell() >= self.segment_bytes:
                self._rotate()
        _obs.JOURNAL_APPEND_SECONDS.observe(time.perf_counter() - t0)

    def append_accepted(self, key, prompt, kw):
        """ACCEPTED is the durability point: fsynced before the caller may
        acknowledge the request to a client."""
        self._append({"k": _ACCEPTED, "key": key,
                      "p": [int(t) for t in prompt], "kw": dict(kw)},
                     critical=True)

    def append_tokens(self, key, seq, tokens):
        """One delivered token batch; ``seq`` is the stream offset of the
        first token, making replay idempotent under record duplication."""
        self._append({"k": _TOKENS, "key": key, "s": int(seq),
                      "t": [int(t) for t in tokens]}, critical=False)

    def append_terminal(self, key, status, error=None):
        payload = {"k": _TERMINAL, "key": key, "st": status.value}
        if error is not None:
            payload["e"] = str(error)
        self._append(payload, critical=True)

    # ---- replay --------------------------------------------------------------
    @staticmethod
    def _apply(state, rec):
        key = rec["key"]
        req = state.get(key)
        if req is None:
            req = state[key] = _Replayed()
        kind = rec["k"]
        if kind == _ACCEPTED:
            req.prompt = [int(t) for t in rec["p"]]
            req.kw = dict(rec["kw"])
        elif kind == _TOKENS:
            seq, toks = int(rec["s"]), rec["t"]
            if seq <= len(req.tokens):
                # duplicate-tolerant: a record replayed twice (compaction
                # raced a crash) extends only past what is already known
                req.tokens.extend(int(t) for t in toks[len(req.tokens) - seq:])
        elif kind == _TERMINAL:
            req.status = _RequestStatus(rec["st"])
            req.error = rec.get("e")
        elif kind == _RESULT:
            req.tokens = [int(t) for t in rec["t"]]
            req.status = _RequestStatus(rec["st"])
            req.error = rec.get("e")

    def replay(self):
        """Read every segment oldest-first; returns ``(state, counts)`` —
        ``state`` maps request key → :class:`_Replayed` in first-seen order,
        ``counts`` tallies records by kind name plus ``"torn"`` for the
        records a CRC/parse failure cost.  A corrupt record ends that
        SEGMENT's replay (everything after a tear is untrusted) but later
        segments still replay — only the active segment can legitimately
        tear, and it is always the last."""
        counts = {name: 0 for name in _KIND_NAMES.values()}
        counts["torn"] = 0
        state = {}
        with self._mu:
            if self._fh is not None:
                self._fh.flush()
            for index in self._segment_indices():
                with open(self._seg_path(index), "rb") as fh:
                    for line in fh:
                        try:
                            rec = _decode(line)
                        except JournalCorruption:
                            counts["torn"] += 1
                            break
                        self._apply(state, rec)
                        counts[_KIND_NAMES[rec["k"]]] += 1
        return state, counts

    # ---- compaction ----------------------------------------------------------
    def compact(self):
        """Fold terminal requests into single RESULT records and drop all
        but the newest ``keep_terminal`` of them; non-terminal requests are
        rewritten as one ACCEPTED + one TOKENS record.  The compacted
        segment is built in a ``.tmp`` file and published with
        ``os.replace`` + directory fsync — a crash at any point leaves
        either the old segments or old + compacted (replay is duplicate-
        tolerant), never a half-written journal.  Returns the number of
        terminal requests dropped."""
        with self._mu:
            # compaction holds _mu across its fsyncs BY DESIGN: appends
            # must not interleave with the segment swap, and the swap is
            # not durable (hence not announceable) until synced
            state, _ = self.replay()
            self._fsync_fh()  # graftlint: disable=concurrency
            self._fh.close()
            old = self._segment_indices()
            compact_index = self._seg_index + 1
            terminal = [(k, r) for k, r in state.items()
                        if r.status is not None]
            dropped = max(0, len(terminal) - self.keep_terminal)
            tmp = self._seg_path(compact_index) + ".tmp"
            with open(tmp, "wb") as fh:
                for key, req in state.items():
                    if req.status is not None:
                        continue
                    fh.write(_encode({"k": _ACCEPTED, "key": key,
                                      "p": req.prompt, "kw": req.kw}))
                    if req.tokens:
                        fh.write(_encode({"k": _TOKENS, "key": key, "s": 0,
                                          "t": req.tokens}))
                for key, req in terminal[dropped:]:
                    payload = {"k": _RESULT, "key": key, "t": req.tokens,
                               "st": req.status.value}
                    if req.error is not None:
                        payload["e"] = req.error
                    fh.write(_encode(payload))
                fh.flush()
                os.fsync(fh.fileno())  # graftlint: disable=concurrency
            os.replace(tmp, self._seg_path(compact_index))
            self._fsync_dir()  # graftlint: disable=concurrency
            for index in old:
                os.unlink(self._seg_path(index))
            self._seg_index = compact_index + 1
            self._open_segment()
            self._fsync_dir()  # graftlint: disable=concurrency
            return dropped

    def stats(self):
        with self._mu:
            indices = self._segment_indices()
            size = sum(os.path.getsize(self._seg_path(i)) for i in indices)
            return {"segments": len(indices), "bytes": size,
                    "appended": self.appended}

    def close(self):
        with self._mu:
            if self._fh is not None:
                if self.fsync != "never":
                    try:
                        # final fsync under _mu: no append may slip in
                        # between it and the close
                        self._fsync_fh()  # graftlint: disable=concurrency
                    except (OSError, _faults.InjectedFault):
                        pass  # closing anyway; replay tolerates the tear
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class DurableRequest:
    """In-memory face of one journaled request: the tokens delivered so
    far, the terminal status once known, and the condition SSE writers park
    on.  ``attached`` counts live client connections; when it drops to zero
    before the request is terminal, ``detach_deadline`` starts the grace
    window after which the plane's pump cancels the orphaned request."""

    __slots__ = ("key", "prompt", "kw", "tokens", "status", "error",
                 "handle", "attached", "detach_deadline", "replayed", "_cv")

    def __init__(self, key, prompt=None, kw=None):
        self.key = key
        self.prompt = prompt
        self.kw = dict(kw or {})
        self.tokens = []
        self.status = None           # RequestStatus, set exactly once
        self.error = None
        self.handle = None           # fleet RequestHandle while being driven
        self.attached = 0
        self.detach_deadline = None
        self.replayed = False        # served from the journal, never re-run
        self._cv = threading.Condition()

    @property
    def terminal(self):
        # under the cv (it wraps an RLock, so holders may re-enter): status
        # flips exactly once, but the lock orders this read after the
        # finish() that also published tokens/error
        with self._cv:
            return self.status is not None

    def publish(self, tokens):
        with self._cv:
            self.tokens.extend(int(t) for t in tokens)
            self._cv.notify_all()

    def finish(self, status, error=None):
        with self._cv:
            if self.status is None:
                self.status = status
                self.error = error
            self._cv.notify_all()

    def wait_terminal(self, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self.status is None:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    raise TimeoutError(
                        f"request {self.key!r} not terminal after {timeout}s")
                self._cv.wait(0.25 if left is None else min(left, 0.25))
            return list(self.tokens), self.status

    def events(self, after=0, heartbeat=None):
        """Yield ``(seq, token)`` for every token with ``seq >= after`` —
        journal-backed history first, then live tokens as the pump lands
        them — until the request is terminal and fully delivered.  With
        ``heartbeat`` set, yields ``None`` whenever that many idle seconds
        pass, mirroring :meth:`ReplicaSet.stream`'s keep-alive contract."""
        seq = max(0, int(after))
        last = time.monotonic()
        while True:
            with self._cv:
                while seq >= len(self.tokens) and self.status is None:
                    slice_ = 0.25 if heartbeat is None \
                        else min(0.25, float(heartbeat))
                    self._cv.wait(slice_)
                    if (heartbeat is not None
                            and time.monotonic() - last >= float(heartbeat)
                            and seq >= len(self.tokens)
                            and self.status is None):
                        break
                batch = self.tokens[seq:]
                done = self.status is not None and not batch
            if done:
                return
            if not batch:
                yield None               # heartbeat (socket-liveness probe)
                last = time.monotonic()
                continue
            for tok in batch:            # yield outside the lock: a slow
                yield seq, int(tok)      # client must not stall the pump
                seq += 1
            last = time.monotonic()


class DurableRequestPlane:
    """Keyed table of :class:`DurableRequest` + the journal + the pumps.

    One pump thread per inflight request drains
    :meth:`ReplicaSet.stream_batches`, journaling each batch before
    publishing it (journal ≥ client, always), then journals the terminal.
    ``detach_ttl`` is the grace window a fully-detached pre-terminal
    request survives before the pump cancels it.  ``compact_every``
    triggers journal compaction after that many terminal requests.
    """

    def __init__(self, replica_set, path, fsync="critical", detach_ttl=30.0,
                 segment_bytes=1 << 20, keep_terminal=512, compact_every=64):
        self.replica_set = replica_set
        self.journal = RequestJournal(path, segment_bytes=segment_bytes,
                                      fsync=fsync,
                                      keep_terminal=keep_terminal)
        self.detach_ttl = float(detach_ttl)
        self.compact_every = int(compact_every)
        self.recovering = False
        self.recovered = 0          # non-terminal requests re-driven
        self._mu = threading.Lock()
        self._table = {}            # key -> DurableRequest
        self._pumps = []
        self._terminal_since_compact = 0
        self._closed = False

    # ---- submission ----------------------------------------------------------
    def get(self, key):
        with self._mu:
            return self._table.get(key)

    def submit(self, key, prompt, kw):
        """Idempotent keyed submit: a known key returns its existing
        :class:`DurableRequest` with ``replayed=True`` semantics (the fleet
        is not touched); a new key is routed, journaled ACCEPTED (fsynced),
        and pumped.  Shed/route failures raise BEFORE journaling — an
        unjournaled request was never accepted."""
        with self._mu:
            existing = self._table.get(key)
            if existing is not None:
                return existing, False
        handle = self.replica_set.submit(prompt, **kw)
        try:
            self.journal.append_accepted(key, prompt, kw)
        except Exception:
            # could not make acceptance durable: the request must not run
            self.replica_set.cancel(handle)
            raise
        req = DurableRequest(key, prompt=list(prompt), kw=kw)
        req.handle = handle
        req.detach_deadline = time.monotonic() + self.detach_ttl
        with self._mu:
            # a racing submit of the same key lost to us only after paying
            # a duplicate engine admission; first journaled wins the table
            won = self._table.setdefault(key, req)
        if won is not req:
            self.replica_set.cancel(handle)
            return won, False
        self._start_pump(req)
        return req, True

    def attach(self, req):
        with req._cv:
            req.attached += 1
            req.detach_deadline = None

    def detach(self, req):
        with req._cv:
            req.attached = max(0, req.attached - 1)
            if req.attached == 0 and req.status is None:
                req.detach_deadline = time.monotonic() + self.detach_ttl

    # ---- pump ----------------------------------------------------------------
    def _start_pump(self, req):
        t = threading.Thread(target=self._pump, args=(req,),
                             name=f"journal-pump-{req.key[:8]}", daemon=True)
        t.start()
        self._pumps.append(t)

    def _pump(self, req):
        rs = self.replica_set
        try:
            # the heartbeat tick doubles as the detach-TTL poll cadence
            tick = max(0.05, min(1.0, self.detach_ttl / 4.0))
            for toks, _status in rs.stream_batches(req.handle,
                                                   heartbeat=tick):
                if self._closed:
                    return
                if toks:
                    seq = len(req.tokens)
                    self.journal.append_tokens(req.key, seq, toks)
                    req.publish(toks)
                with req._cv:
                    deadline = (req.detach_deadline
                                if req.attached == 0 else None)
                if deadline is not None and time.monotonic() > deadline:
                    # every client left and the grace window lapsed: stop
                    # decoding for nobody (the terminal lands as CANCELLED)
                    rs.cancel(req.handle)
            status = rs.status(req.handle)
            error = (rs.request_error(req.handle)
                     if status is _RequestStatus.FAILED else None)
        except Exception as e:  # noqa: BLE001 — journal faults land here
            status, error = _RequestStatus.FAILED, repr(e)
        if self._closed:
            return
        try:
            self.journal.append_terminal(req.key, status, error=error)
        except Exception as e:  # noqa: BLE001
            # the terminal could not be made durable; the in-memory request
            # still terminates (recovery would re-drive it, which is safe)
            error = error or repr(e)
        req.finish(status, error)
        self._maybe_compact()

    def _maybe_compact(self):
        with self._mu:
            self._terminal_since_compact += 1
            due = self._terminal_since_compact >= self.compact_every
            if due:
                self._terminal_since_compact = 0
        if due:
            try:
                self.journal.compact()
            except OSError:
                pass  # compaction is an optimization; appends still work

    # ---- crash recovery ------------------------------------------------------
    def recover(self):
        """Replay the journal into the table: terminal requests become
        replay-only entries (idempotency hits are served from them),
        non-terminal ones are re-driven onto the fleet with their journaled
        tokens as ``resume_tokens`` — byte-identical continuation for
        greedy/fixed-seed sampling.  Sets ``recovering`` for the duration
        so the gateway can shed with Retry-After instead of racing the
        replay."""
        self.recovering = True
        try:
            state, counts = self.journal.replay()
            for kind in ("accepted", "tokens", "terminal", "result"):
                if counts[kind]:
                    _obs.JOURNAL_REPLAYED.inc(counts[kind], kind=kind)
            if sum(counts[k] for k in
                   ("accepted", "tokens", "terminal", "result")):
                _obs.GATEWAY_RECOVERIES.inc()
            for key, rep in state.items():
                req = DurableRequest(key, prompt=rep.prompt, kw=rep.kw)
                req.tokens = list(rep.tokens)
                req.replayed = True
                if rep.status is not None:
                    req.status, req.error = rep.status, rep.error
                    with self._mu:
                        self._table.setdefault(key, req)
                    continue
                with self._mu:
                    if self._table.setdefault(key, req) is not req:
                        continue  # a live submit beat the replay to it
                self._redrive(req)
                self.recovered += 1
        finally:
            self.recovering = False

    def _redrive(self, req):
        """Resubmit one journaled non-terminal request.  The journaled
        token prefix re-prefills via ``resume_tokens``; a request whose
        budget is already spent (or that already hit EOS) just needs its
        terminal pinned and journaled."""
        kw = dict(req.kw)
        emitted = list(req.tokens)
        remaining = int(kw.get("max_new_tokens", 16)) - len(emitted)
        eos = kw.get("eos_token_id")
        hit_eos = eos is not None and emitted and emitted[-1] == eos
        if remaining <= 0 or hit_eos:
            status = (_RequestStatus.EOS if hit_eos
                      else _RequestStatus.FINISHED)
            try:
                self.journal.append_terminal(req.key, status)
            except (OSError, _faults.InjectedFault):
                pass  # best-effort: an unjournaled terminal just re-pins
                      # the same way on the next replay
            req.finish(status)
            return
        if emitted:
            kw["max_new_tokens"] = remaining
            kw["resume_tokens"] = emitted
        try:
            _faults.FAULTS.maybe_fire("gateway.recover", key=req.key)
            req.handle = self.replica_set.submit(req.prompt, **kw)
        except (ShedError, ReplicaDeadError, _faults.InjectedFault) as e:
            # the fleet would not take it back: fail it durably rather than
            # leave a request that is neither running nor terminal
            try:
                self.journal.append_terminal(req.key, _RequestStatus.FAILED,
                                             error=repr(e))
            except (OSError, _faults.InjectedFault):
                pass  # the FAILED pin stays in memory; replay re-derives it
            req.finish(_RequestStatus.FAILED, repr(e))
            return
        req.detach_deadline = time.monotonic() + self.detach_ttl
        self._start_pump(req)

    # ---- introspection / lifecycle ------------------------------------------
    def depth(self):
        """Non-terminal requests currently tracked (the /healthz number)."""
        with self._mu:
            return sum(1 for r in self._table.values() if r.status is None)

    def health(self):
        h = {"depth": self.depth(), "recovering": self.recovering,
             "recovered": self.recovered}
        h.update(self.journal.stats())
        return h

    def close(self):
        """Stop pumping and close the journal.  Inflight requests are NOT
        cancelled — their lack of a journaled terminal is exactly what a
        crash leaves behind, so a later ``recover()`` resumes them."""
        self._closed = True
        for t in self._pumps:
            t.join(timeout=5.0)
        self.journal.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
