"""paddle_tpu.inference.frontend — the serving front door.

Turns N single-caller :class:`~paddle_tpu.inference.serving.LLMEngine`
replicas into one service:

- :mod:`.replica` — per-replica step-loop threads behind a thread-safe
  submit/stream/cancel facade (:class:`ReplicaSet`), with replica death as a
  first-class typed event.
- :mod:`.router` — prefix-cache-aware routing on the engine's own chain-hash
  page keys (:class:`PrefixAffinityRouter`), round-robin baseline.
- :mod:`.admission` — SLO-aware shedding before a request reaches a replica
  (:class:`SLOAdmission`), typed :class:`ShedError`.
- :mod:`.gateway` — stdlib streaming HTTP/SSE server
  (``POST /v1/completions``, ``/healthz``, ``/metrics``).
- :mod:`.journal` — the durable request plane: a CRC'd write-ahead request
  journal plus the keyed table that makes gateway submits idempotent
  (``Idempotency-Key``), SSE streams client-resumable (``Last-Event-ID``),
  and gateway ``kill -9`` recoverable (journal replay re-drives unfinished
  requests through the engines' ``resume_tokens`` machinery).
- :mod:`.loadgen` — deterministic trace-driven load generation for tests and
  the bench frontend extra.
- :mod:`.rpc` / :mod:`.worker` / :mod:`.supervisor` / :mod:`.fleet` — the
  self-healing multi-process fleet: each replica runs its engine in its own
  OS process behind a socket RPC, holds a TTL lease on the membership plane
  (:mod:`paddle_tpu.distributed.membership`), is respawned by a
  crash-loop-aware supervisor, and joins/leaves gateway routing via
  membership events (:class:`FleetReplicaSet`, a ReplicaSet drop-in with
  zero-token crash requeue).

Quick start::

    from paddle_tpu.inference.frontend import ReplicaSet, start_gateway

    rs = ReplicaSet([engine_a, engine_b])           # threads start here
    gw = start_gateway(rs, port=8000)
    ...  # POST http://127.0.0.1:8000/v1/completions
    gw.close(); rs.close()
"""
from .admission import (AdmissionDecision, AlwaysAdmit,  # noqa: F401
                        ShedError, SLOAdmission)
from .fleet import FleetReplicaSet, RemoteReplica  # noqa: F401
from .gateway import Gateway, start_gateway  # noqa: F401
from .journal import (DurableRequest, DurableRequestPlane,  # noqa: F401
                      RequestJournal)
from .loadgen import (http_completion, make_trace,  # noqa: F401
                      run_closed_loop, summarize)
from .replica import (EngineReplica, ReplicaDeadError,  # noqa: F401
                      ReplicaSet, RequestHandle, StuckStepError)
from .router import (PrefixAffinityRouter, RouteDecision,  # noqa: F401
                     RoundRobinRouter)
from .rpc import RpcClient, RpcError, RpcServer  # noqa: F401
from .supervisor import WorkerSupervisor  # noqa: F401
from .worker import WorkerServer  # noqa: F401

__all__ = [
    "ReplicaSet", "EngineReplica", "RequestHandle", "ReplicaDeadError",
    "StuckStepError",
    "PrefixAffinityRouter", "RoundRobinRouter", "RouteDecision",
    "SLOAdmission", "AlwaysAdmit", "AdmissionDecision", "ShedError",
    "Gateway", "start_gateway",
    "RequestJournal", "DurableRequest", "DurableRequestPlane",
    "make_trace", "run_closed_loop", "summarize", "http_completion",
    "RpcServer", "RpcClient", "RpcError",
    "WorkerServer", "WorkerSupervisor",
    "RemoteReplica", "FleetReplicaSet",
]
