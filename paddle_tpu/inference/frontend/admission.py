"""SLO-aware admission control for the serving front door.

The engine already sheds at its own door (``max_waiting`` queue bound +
page-pressure watermark inside ``add_request``), but by then the request has
crossed the network, been routed, and consumed a replica's admission path.
This layer decides *before* routing, from the same signals the engine
exports — queue depth, page-pool pressure (always-on ``health()`` counters,
mirrored by the ``serving_queue_depth`` / ``serving_free_pages`` gauges) and
observed TTFT (the ``serving_ttft_seconds`` histogram, plus a local recent
window so the SLO check also works while observability is disabled).

A refusal is a typed :class:`ShedError` carrying the reason and a
``retry_after`` hint; the gateway maps it to ``429 Too Many Requests`` with
a ``Retry-After`` header, mirroring how the engine's own SHED status is
reported.
"""
from __future__ import annotations

import threading
from collections import deque

from ... import observability as _obs

__all__ = ["AdmissionDecision", "ShedError", "SLOAdmission", "AlwaysAdmit"]


class ShedError(RuntimeError):
    """Request refused before reaching a replica.  ``reason`` is the
    admission rule that fired; ``retry_after`` (seconds) is the backoff hint
    surfaced as the HTTP ``Retry-After`` header."""

    def __init__(self, reason, retry_after=1.0):
        super().__init__(f"request shed by admission control ({reason})")
        self.reason = reason
        self.retry_after = float(retry_after)

    def __reduce__(self):
        # keep reason/retry_after across pickling (the worker RPC ships
        # sheds back to the gateway as exception objects)
        return (ShedError, (self.reason, self.retry_after))


class AdmissionDecision:
    """Outcome of one admission check: ``admit`` plus, when refused, the
    rule that fired and the retry hint."""

    __slots__ = ("admit", "reason", "retry_after")

    def __init__(self, admit, reason=None, retry_after=1.0):
        self.admit = bool(admit)
        self.reason = reason
        self.retry_after = float(retry_after)

    def __repr__(self):
        return (f"AdmissionDecision(admit={self.admit}, "
                f"reason={self.reason!r})")


class AlwaysAdmit:
    """Null policy — every request passes.  The default when a ReplicaSet
    is built without an admission policy."""

    def decide(self, replicas):
        return AdmissionDecision(True)

    def observe_ttft(self, seconds):
        """Accepted and ignored — keeps the policy interface uniform."""

    def observe_tpot(self, seconds):
        """Accepted and ignored — keeps the policy interface uniform."""


class SLOAdmission:
    """Shed when serving the request would blow the SLO rather than after.

    Rules, checked in order (first refusal wins):

    ``queue_full``     every live replica's waiting queue is at
                       ``max_queue_per_replica`` — admitting only deepens
                       the backlog the engines will shed anyway.
    ``page_pressure``  even the best replica's reclaimable page ratio
                       (free + LRU-parked over total) is below
                       ``min_free_page_ratio`` while it has a backlog — new
                       prefills would immediately preempt running requests.
    ``ttft_slo``       the recent mean TTFT exceeds ``ttft_slo`` seconds.
                       Observations come from :meth:`observe_ttft` (the
                       ReplicaSet feeds finished requests' engine-measured
                       TTFT); with no local window yet the check falls back
                       to the ``serving_ttft_seconds`` histogram when
                       observability is enabled, and otherwise admits.
    ``tpot_slo``       the recent mean time-per-output-token exceeds
                       ``tpot_slo`` seconds — the decode-cadence twin of the
                       TTFT rule, so admission also backs off when decode
                       batches are saturated even while first tokens still
                       arrive on time.  Fed by :meth:`observe_tpot` (the
                       ReplicaSet reports finished requests' whole-life
                       TPOT); the no-window fallback is the
                       ``serving_token_latency_seconds`` histogram.

    All thresholds are optional; an ``SLOAdmission()`` with defaults only
    enforces the queue bound.
    """

    def __init__(self, max_queue_per_replica=64, min_free_page_ratio=0.0,
                 ttft_slo=None, tpot_slo=None, window=64, retry_after=1.0):
        self.max_queue = (None if max_queue_per_replica is None
                          else int(max_queue_per_replica))
        self.min_free_ratio = float(min_free_page_ratio)
        self.ttft_slo = None if ttft_slo is None else float(ttft_slo)
        self.tpot_slo = None if tpot_slo is None else float(tpot_slo)
        self.retry_after = float(retry_after)
        self._lock = threading.Lock()
        self._ttfts = deque(maxlen=int(window))
        self._tpots = deque(maxlen=int(window))

    def observe_ttft(self, seconds):
        """Feed one finished request's TTFT into the recent window."""
        if seconds is None:
            return
        with self._lock:
            self._ttfts.append(float(seconds))

    def observe_tpot(self, seconds):
        """Feed one finished request's per-token decode latency (its
        whole-life TPOT) into the recent window."""
        if seconds is None:
            return
        with self._lock:
            self._tpots.append(float(seconds))

    def _window_or_histogram_mean(self, window, histogram):
        with self._lock:
            if window:
                return sum(window) / len(window)
        if not _obs.enabled():
            return None
        snap = _obs.snapshot(prefix=histogram)
        series = snap.get(histogram, {}).get("series", ())
        total = sum(s["sum"] for s in series)
        count = sum(s["count"] for s in series)
        return (total / count) if count else None

    def _recent_mean_ttft(self):
        return self._window_or_histogram_mean(self._ttfts,
                                              "serving_ttft_seconds")

    def _recent_mean_tpot(self):
        return self._window_or_histogram_mean(
            self._tpots, "serving_token_latency_seconds")

    def decide(self, replicas):
        """One admission check against the live replicas' current state."""
        healths = [r.health() for r in replicas]
        if not healths:
            return AdmissionDecision(False, "no_replicas", self.retry_after)
        if self.max_queue is not None and all(
                h["waiting"] >= self.max_queue for h in healths):
            return AdmissionDecision(False, "queue_full", self.retry_after)
        if self.min_free_ratio > 0.0:
            def _ratio(h):
                # host-tier headroom counts: LRU pages a spill tier could
                # absorb are reclaimable WITHOUT recompute loss, so a
                # replica with host headroom sheds later (capped at the
                # reclaimable set — headroom beyond it frees nothing)
                total = max(1, h["total_pages"])
                headroom = min(h.get("host_headroom_pages") or 0,
                               h["reclaimable_pages"])
                return (h["free_pages"] + h["reclaimable_pages"]
                        + headroom) / total
            if all(h["waiting"] and _ratio(h) < self.min_free_ratio
                   for h in healths):
                return AdmissionDecision(False, "page_pressure",
                                         self.retry_after)
        if self.ttft_slo is not None:
            mean = self._recent_mean_ttft()
            if mean is not None and mean > self.ttft_slo:
                return AdmissionDecision(False, "ttft_slo", self.retry_after)
        if self.tpot_slo is not None:
            mean = self._recent_mean_tpot()
            if mean is not None and mean > self.tpot_slo:
                return AdmissionDecision(False, "tpot_slo", self.retry_after)
        return AdmissionDecision(True)
