"""Threaded engine replicas behind one thread-safe facade.

``LLMEngine`` is single-caller by design: ``step()`` mutates slot tables,
page refcounts, and the prefix index with no internal locking.  This module
keeps that invariant while serving many concurrent callers by giving each
replica ONE ``threading.Condition`` that serializes every engine touch — the
step loop holds it per step, and ``submit`` / ``new_tokens`` / ``cancel`` /
``health`` take it per call.  Streams block on the condition and are woken
after every step, so token latency is one notify away from the engine's own
cadence rather than a polling interval.

Replica death is a first-class event: when the step loop dies (an armed
``frontend.step`` fault, or an error that escapes the engine's own
step-isolation machinery) the replica finalizes every inflight request as
FAILED via ``LLMEngine.fail_all`` — streams observe a typed terminal status
instead of hanging — drops its prefix-key mirror from the router, and is
excluded from routing from then on.

Fault points (see :mod:`paddle_tpu.testing.faults`): ``frontend.route``
fires before routing, ``frontend.submit`` after a replica is chosen (ctx has
``replica``), ``frontend.step`` inside a replica's step loop (ctx has
``replica``) — the chaos tests use the last to kill a replica mid-stream.
"""
from __future__ import annotations

import threading
import time

from ... import observability as _obs
from ...testing import faults as _faults
from ..serving import RequestStatus as _RequestStatus
from .admission import AlwaysAdmit, ShedError
from .router import PrefixAffinityRouter

__all__ = ["ReplicaDeadError", "EngineReplica", "RequestHandle", "ReplicaSet"]


class ReplicaDeadError(RuntimeError):
    """Raised when submitting to a dead replica, or when no replica in the
    set is alive."""


class EngineReplica:
    """One engine + the lock that makes it multi-caller safe + the thread
    that drives it.  All public methods are thread-safe."""

    def __init__(self, name, engine, router=None, poll_interval=0.05):
        self.name = str(name)
        self.engine = engine
        self.router = router
        self.alive = True
        self.error = None
        self._cv = threading.Condition(threading.RLock())
        self._stop = False
        self._thread = None
        self._poll = float(poll_interval)
        if router is not None:
            # called from inside step() while the step thread holds our
            # condition; the router only takes its own (leaf) lock.
            engine.cache_event_listener = (
                lambda event, key: router.note_event(self.name, event, key))

    # ---- lifecycle -----------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"replica-{self.name}", daemon=True)
            self._thread.start()
        return self

    def close(self):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _has_work(self):
        eng = self.engine
        return bool(eng._waiting) or any(s is not None for s in eng._slots)

    def _loop(self):
        while True:
            with self._cv:
                if self._stop:
                    return
                if not self._has_work():
                    self._cv.wait(self._poll)
                    continue
                try:
                    if _faults.FAULTS.active:
                        _faults.FAULTS.raise_if("frontend.step",
                                                replica=self.name)
                    self.engine.step()
                except Exception as e:  # noqa: BLE001 — replica death boundary
                    self._die(e)
                    return
                self._cv.notify_all()

    def _die(self, error):
        """Step loop died: fail every inflight request with a typed terminal
        status, drop our prefix mirror, and stop accepting work.  Caller
        holds the condition."""
        self.alive = False
        self.error = error
        try:
            self.engine.fail_all(error)
        finally:
            if self.router is not None:
                self.router.forget(self.name)
            self._cv.notify_all()

    # ---- request facade ------------------------------------------------------
    def load(self):
        """Scheduling pressure: waiting + active requests (the router's
        tie-breaker and the least-loaded fallback metric)."""
        with self._cv:
            eng = self.engine
            return len(eng._waiting) + sum(
                1 for s in eng._slots if s is not None)

    def submit(self, prompt_ids, **kw):
        """Thread-safe ``add_request``; wakes the step loop.  The returned
        rid may already be terminal SHED (engine-level admission)."""
        with self._cv:
            if not self.alive:
                raise ReplicaDeadError(
                    f"replica {self.name!r} is dead: {self.error!r}")
            rid = self.engine.add_request(prompt_ids, **kw)
            self._cv.notify_all()
            return rid

    def poll(self, rid, timeout=None):
        """Block until ``rid`` has new tokens or is terminal; returns
        ``(tokens, status)``.  ``timeout`` bounds the wait — on expiry the
        current (possibly empty) increment is returned with a live status."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                toks = self.engine.new_tokens(rid)
                status = self.engine.status(rid)
                if toks or status.terminal:
                    return toks, status
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return [], status
                    self._cv.wait(min(left, self._poll))
                else:
                    self._cv.wait(self._poll)

    def cancel(self, rid):
        with self._cv:
            ok = self.engine.cancel(rid)
            self._cv.notify_all()
            return ok

    def status(self, rid):
        with self._cv:
            return self.engine.status(rid)

    def result(self, rid):
        with self._cv:
            return list(self.engine.result(rid))

    def request_error(self, rid):
        with self._cv:
            return self.engine.error(rid)

    def ttft(self, rid):
        with self._cv:
            try:
                return self.engine.ttft(rid)
            except KeyError:
                return None

    def tpot(self, rid):
        with self._cv:
            try:
                return self.engine.tpot(rid)
            except KeyError:
                return None

    def prefix_keys(self):
        """Chain keys resident in this replica's prefix cache — the fleet
        layer snapshots these over RPC to warm the gateway-side router for
        replicas whose cache events never cross the process boundary."""
        with self._cv:
            fn = getattr(self.engine, "prefix_keys", None)
            return list(fn()) if fn is not None else []

    def health(self):
        with self._cv:
            h = self.engine.health()
        h["replica"] = self.name
        h["alive"] = self.alive
        h["error"] = repr(self.error) if self.error is not None else None
        return h

    def metrics(self):
        with self._cv:
            return self.engine.metrics()


class RequestHandle:
    """Where a routed request lives: the replica, its rid there, and the
    submit timestamp the stream-duration histogram measures from.

    For crash recovery the handle also remembers what was submitted
    (``prompt_ids`` / ``kw``), how many tokens the caller has already
    received (``streamed``), and whether the request was already requeued
    once (``requeued``) — a replica death with ``streamed == 0`` may be
    transparently resubmitted elsewhere, anything else fails typed via
    ``final_status`` / ``final_error``."""

    __slots__ = ("replica", "rid", "t0", "_accounted", "prompt_ids", "kw",
                 "streamed", "requeued", "final_status", "final_error")

    def __init__(self, replica, rid, prompt_ids=None, kw=None):
        self.replica = replica
        self.rid = rid
        self.t0 = time.perf_counter()
        self._accounted = False
        self.prompt_ids = prompt_ids
        self.kw = kw or {}
        self.streamed = 0
        self.requeued = False
        self.final_status = None
        self.final_error = None

    def __repr__(self):
        return f"RequestHandle({self.replica.name!r}, rid={self.rid})"


class ReplicaSet:
    """N replicas behind one submit/stream/cancel facade.

    ``engines`` may be constructed engines or a list of (name, engine)
    pairs; default names are ``r0..rN-1``.  The default router is
    :class:`~.router.PrefixAffinityRouter` fed by every replica's cache
    events; pass ``router=RoundRobinRouter()`` for the affinity-blind
    baseline.  ``admission`` is consulted before routing — a refusal raises
    :class:`~.admission.ShedError` without touching any replica.

    ``requeue=True`` turns on crash recovery: when a replica dies under an
    inflight request that has streamed ZERO tokens, the request is
    transparently resubmitted once onto a surviving replica (routed warm
    through the prefix-affinity router); a request that already streamed
    tokens fails typed FAILED as before (re-emitting its prefix would
    corrupt the caller's stream).  The multi-process fleet enables this —
    the in-process default stays off, preserving fail-fast semantics.
    """

    def __init__(self, engines, router=None, admission=None, names=None,
                 start=True, poll_interval=0.05, requeue=False):
        engines = list(engines)
        if not engines:
            raise ValueError("ReplicaSet needs at least one engine")
        if engines and isinstance(engines[0], tuple):
            names = [n for n, _ in engines]
            engines = [e for _, e in engines]
        if names is None:
            names = [f"r{i}" for i in range(len(engines))]
        if router is None:
            router = PrefixAffinityRouter(page_size=engines[0].page)
        self.router = router
        self.admission = admission if admission is not None else AlwaysAdmit()
        self.requeue = bool(requeue)
        self.replicas = [
            EngineReplica(n, e, router=router, poll_interval=poll_interval)
            for n, e in zip(names, engines)]
        self._by_name = {r.name: r for r in self.replicas}
        if start:
            self.start()

    # ---- lifecycle -----------------------------------------------------------
    def start(self):
        for r in self.replicas:
            r.start()
        return self

    def close(self):
        for r in self.replicas:
            r.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def replica(self, name):
        return self._by_name[name]

    def alive_replicas(self):
        return [r for r in self.replicas if r.alive]

    def add_replica(self, replica, start=False):
        """Join a pre-built replica (in-process or remote) into routing;
        replaces any previous replica of the same name."""
        old = self._by_name.get(replica.name)
        if old is not None:
            self.remove_replica(old.name)
        self.replicas.append(replica)
        self._by_name[replica.name] = replica
        if start and hasattr(replica, "start"):
            replica.start()
        return replica

    def remove_replica(self, name):
        """Drop a replica from routing (its inflight handles hit the death
        path on their next poll); returns the removed replica or None."""
        rep = self._by_name.pop(name, None)
        if rep is not None:
            self.replicas.remove(rep)
            self.router.forget(name)
        return rep

    # ---- request facade ------------------------------------------------------
    def submit(self, prompt_ids, **kw):
        """Admit, route, and submit one request; returns a
        :class:`RequestHandle`.  Raises :class:`~.admission.ShedError` on
        admission refusal and :class:`ReplicaDeadError` with no live
        replicas."""
        if _faults.FAULTS.active:
            _faults.FAULTS.raise_if("frontend.route")
        alive = self.alive_replicas()
        if not alive:
            raise ReplicaDeadError("no live replicas")
        decision = self.admission.decide(alive)
        if not decision.admit:
            _obs.FRONTEND_SHED.inc(reason=decision.reason)
            _obs.FRONTEND_REQUESTS.inc(outcome="shed")
            raise ShedError(decision.reason, decision.retry_after)
        # a replica can die between routing and submit (remote worker
        # killed); reroute over the survivors instead of failing the request
        tried = set()
        while True:
            candidates = [r for r in self.alive_replicas()
                          if r.name not in tried]
            if not candidates:
                raise ReplicaDeadError("no live replicas")
            route = self.router.route(prompt_ids, candidates)
            rep = route.replica
            if _faults.FAULTS.active:
                _faults.FAULTS.raise_if("frontend.submit", replica=rep.name)
            try:
                rid = rep.submit(prompt_ids, **kw)
                break
            except ReplicaDeadError:
                tried.add(rep.name)
        if rep.status(rid) is _RequestStatus.SHED:
            # the engine's own admission control refused it (queue bound /
            # page watermark); surface it exactly like a frontend shed
            _obs.FRONTEND_SHED.inc(reason="engine")
            _obs.FRONTEND_REQUESTS.inc(outcome="shed")
            raise ShedError("engine", decision.retry_after)
        _obs.FRONTEND_ROUTED.inc(replica=rep.name, reason=route.reason)
        _obs.FRONTEND_INFLIGHT.inc()
        return RequestHandle(rep, rid, prompt_ids=list(prompt_ids),
                             kw=dict(kw))

    def _account(self, handle, status):
        """First terminal observation of a request: outcome counter, inflight
        gauge, stream-duration histogram, and the admission policy's TTFT and
        TPOT windows.  Idempotent per handle."""
        if handle._accounted:
            return
        handle._accounted = True
        _obs.FRONTEND_REQUESTS.inc(outcome=status.value)
        _obs.FRONTEND_INFLIGHT.inc(-1)
        _obs.FRONTEND_STREAM_SECONDS.observe(time.perf_counter() - handle.t0)
        try:
            self.admission.observe_ttft(handle.replica.ttft(handle.rid))
            observe_tpot = getattr(self.admission, "observe_tpot", None)
            if observe_tpot is not None:
                observe_tpot(handle.replica.tpot(handle.rid))
        except ReplicaDeadError:
            pass  # the replica died under us; its latencies died with it

    # ---- replica-death handling ---------------------------------------------
    def _poll_handle(self, handle, timeout):
        """``replica.poll`` with fleet-level crash recovery: a dead replica
        either requeues the handle (zero tokens streamed, once) or pins a
        typed FAILED terminal on it."""
        if handle.final_status is not None:
            return [], handle.final_status
        try:
            toks, status = handle.replica.poll(handle.rid, timeout=timeout)
        except ReplicaDeadError as e:
            return [], self._on_replica_death(handle, e)
        handle.streamed += len(toks)
        return toks, status

    def _on_replica_death(self, handle, error):
        """The replica under ``handle`` died (lease expiry / RPC failure /
        in-process step death).  Returns the handle's new status: a live
        one after a successful requeue, else the pinned FAILED."""
        if (self.requeue and not handle.requeued and handle.streamed == 0
                and handle.prompt_ids is not None):
            try:
                alive = [r for r in self.alive_replicas()
                         if r is not handle.replica]
                if alive:
                    route = self.router.route(handle.prompt_ids, alive)
                    rid = route.replica.submit(handle.prompt_ids,
                                               **handle.kw)
                    if route.replica.status(rid) is not _RequestStatus.SHED:
                        handle.replica, handle.rid = route.replica, rid
                        handle.requeued = True
                        _obs.FRONTEND_REQUEUED.inc()
                        _obs.FRONTEND_ROUTED.inc(replica=route.replica.name,
                                                 reason="requeue")
                        return route.replica.status(rid)
            except (ReplicaDeadError, ShedError):
                pass  # no survivor could take it: fall through to FAILED
        handle.final_status = _RequestStatus.FAILED
        handle.final_error = error
        self._account(handle, _RequestStatus.FAILED)
        return _RequestStatus.FAILED

    def stream(self, handle, poll_timeout=0.5):
        """Yield ``handle``'s tokens as they are emitted, one int at a time,
        until the request is terminal.  Check ``self.status(handle)`` after
        exhaustion for the terminal status."""
        while True:
            toks, status = self._poll_handle(handle, poll_timeout)
            yield from toks
            if status.terminal and not toks:
                # drain once more: tokens emitted by the finalizing step
                # land before the terminal status is visible
                yield from self._poll_handle(handle, 0)[0]
                self._account(handle, status)
                return

    def result(self, handle, timeout=None):
        """Block until terminal; returns ``(tokens, status)``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            _, status = self._poll_handle(handle, 1.0)
            if status.terminal:
                self._account(handle, status)
                if handle.final_status is not None:
                    return [], handle.final_status
                return handle.replica.result(handle.rid), status
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"{handle!r} not terminal after {timeout}s")

    def status(self, handle):
        if handle.final_status is not None:
            return handle.final_status
        try:
            return handle.replica.status(handle.rid)
        except ReplicaDeadError as e:
            return self._on_replica_death(handle, e)

    def cancel(self, handle):
        if handle.final_status is not None:
            return False
        try:
            return handle.replica.cancel(handle.rid)
        except ReplicaDeadError:
            return False

    def request_error(self, handle):
        if handle.final_error is not None:
            return repr(handle.final_error)
        try:
            return handle.replica.request_error(handle.rid)
        except ReplicaDeadError as e:
            return repr(e)

    def health(self):
        """Per-replica health snapshots keyed by replica name."""
        return {r.name: r.health() for r in self.replicas}

    def metrics(self):
        """Per-replica registry snapshots keyed by replica name."""
        return {r.name: r.metrics() for r in self.replicas}
