"""Threaded engine replicas behind one thread-safe facade.

``LLMEngine`` is single-caller by design: ``step()`` mutates slot tables,
page refcounts, and the prefix index with no internal locking.  This module
keeps that invariant while serving many concurrent callers by giving each
replica ONE ``threading.Condition`` that serializes every engine touch — the
step loop holds it per step, and ``submit`` / ``cancel`` / ``health`` take
it per call.  Token DELIVERY does not ride that lock: each step publishes
new tokens and statuses into a per-request outbox under a light condition
of its own, and ``poll`` waits there — token latency stays one notify away
from the engine's cadence, and a timed poll keeps its deadline even while
a step holds the engine condition for seconds (jit compile, paced chaos
steps), which is what SSE keep-alive heartbeats ride on.

Replica death is a first-class event: when the step loop dies (an armed
``frontend.step`` fault, or an error that escapes the engine's own
step-isolation machinery) the replica finalizes every inflight request as
FAILED via ``LLMEngine.fail_all`` — streams observe a typed terminal status
instead of hanging — drops its prefix-key mirror from the router, and is
excluded from routing from then on.  With ``requeue=True`` the
:class:`ReplicaSet` turns that death into recovery instead: zero-streamed
requests requeue onto a survivor, partially-streamed ones resume with
their emitted history (see :meth:`ReplicaSet._resume`).

Fault points (see :mod:`paddle_tpu.testing.faults`): ``frontend.route``
fires before routing, ``frontend.submit`` after a replica is chosen (ctx has
``replica``), ``frontend.step`` inside a replica's step loop (ctx has
``replica``) — the chaos tests use the last to kill a replica mid-stream —
and ``frontend.resume`` inside the durable-resume attempt (ctx has the dead
``replica``; arming it fails the one resume attempt, the only path on which
a partially-streamed request may end FAILED).
"""
from __future__ import annotations

import functools
import threading
import time
from concurrent.futures import ThreadPoolExecutor, as_completed

from ... import observability as _obs
from ...observability import flight as _flight
from ...core.retry import RetryPolicy, retry_call
from ...testing import faults as _faults
from ..serving import RequestStatus as _RequestStatus
from ..serving import prefix_page_keys
from .admission import AlwaysAdmit, ShedError
from .router import PrefixAffinityRouter

__all__ = ["ReplicaDeadError", "StuckStepError", "EngineReplica",
           "RequestHandle", "ReplicaSet"]


class _TransientPull(Exception):
    """Private wrapper around a transient ``kv.peer_pull`` error so
    :func:`retry_call` retries exactly those; any other failure abandons
    the pull and the request recomputes its prefix (lossless fallback)."""

    def __init__(self, err):
        # forward err itself (str() is identical for a 1-arg Exception) so
        # the default __reduce__ round-trips the wrapper by value (CT102)
        super().__init__(err)
        self.err = err


class ReplicaDeadError(RuntimeError):
    """Raised when submitting to a dead replica, or when no replica in the
    set is alive."""


class StuckStepError(RuntimeError):
    """A replica step exceeded ``step_wall_timeout`` — the watchdog promoted
    the gray failure (wedged device, deadlocked collective) to a typed
    replica death so inflight streams fail over instead of hanging."""


class EngineReplica:
    """One engine + the lock that makes it multi-caller safe + the thread
    that drives it.  All public methods are thread-safe.

    Token delivery is decoupled from the engine lock: after every step the
    loop PUBLISHES each request's new tokens and status into a per-request
    outbox guarded by its own light condition, and :meth:`poll` waits on
    that outbox alone.  The engine condition is held for a step's whole
    duration (first-call jit compile runs seconds; a fault-paced slow step
    sleeps inside it), and a lock release followed by an immediate
    re-acquire routinely barges past timed waiters — a poller contending on
    the engine lock can starve for an entire decode burst and then receive
    the whole batch at once.  Waiting on the outbox instead keeps timed
    polls inside their deadline (SSE heartbeats depend on this) and token
    latency at one notify."""

    def __init__(self, name, engine, router=None, poll_interval=0.05,
                 step_wall_timeout=None):
        self.name = str(name)
        self.engine = engine
        self.router = router
        self.alive = True
        self.error = None
        self._cv = threading.Condition(threading.RLock())
        # rid -> {"toks": [undelivered], "status": last published} — written
        # by _publish (engine condition held), read/drained by poll under
        # the light condition only.  Lock order: engine cv, then outbox cv.
        self._out_cv = threading.Condition()
        self._out = {}
        self._stop = False
        self._thread = None
        self._poll = float(poll_interval)
        self.step_wall_timeout = (None if step_wall_timeout is None
                                  else float(step_wall_timeout))
        self._step_t0 = None        # monotonic start of the inflight step
        self._watchdog = None
        if router is not None:
            # called from inside step() while the step thread holds our
            # condition; the router only takes its own (leaf) lock.
            engine.cache_event_listener = (
                lambda event, key: router.note_event(self.name, event, key))

    # ---- lifecycle -----------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"replica-{self.name}", daemon=True)
            self._thread.start()
        if self.step_wall_timeout is not None and self._watchdog is None:
            self._watchdog = threading.Thread(
                target=self._watch_steps, name=f"watchdog-{self.name}",
                daemon=True)
            self._watchdog.start()
        return self

    def close(self):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self._watchdog is not None:
            self._watchdog.join(timeout=10.0)
            self._watchdog = None

    def _has_work(self):
        eng = self.engine
        return bool(eng._waiting) or any(s is not None for s in eng._slots)

    def _loop(self):
        # engine-side span events (prefill/decode/first_token/terminal) all
        # record from this thread — label them with the replica's name so a
        # merged trace shows which replica served each phase
        _flight.set_proc_label(f"replica:{self.name}")
        while True:
            with self._cv:
                if self._stop:
                    return
                if not self._has_work():
                    self._cv.wait(self._poll)
                    continue
                try:
                    _faults.FAULTS.maybe_fire("frontend.step",
                                              replica=self.name)
                    self._step_t0 = time.monotonic()
                    self.engine.step()
                except Exception as e:  # noqa: BLE001 — replica death boundary
                    self._step_t0 = None
                    self._die(self.error if not self.alive else e)
                    return
                self._step_t0 = None
                if not self.alive:
                    # the watchdog declared this step stuck while it ran;
                    # it could not touch the engine (we held the condition)
                    # so finalize engine-side state now that we are back
                    self._die(self.error)
                    return
                self._publish()
                self._cv.notify_all()

    def _watch_steps(self):
        """Wall-clock watchdog for the step loop: a step running longer
        than ``step_wall_timeout`` is a gray failure (wedged device,
        deadlocked collective) that would hang every stream on this replica
        forever — promote it to a typed replica death.  The stuck step
        HOLDS the engine condition, so the watchdog must not take it:
        it marks the replica dead, fails the outbox directly (pollers fail
        over immediately), and leaves engine-side finalization to the step
        loop whenever the wedged step finally returns."""
        timeout = self.step_wall_timeout
        tick = max(0.01, min(0.25, timeout / 4.0))
        # lock-free reads BY DESIGN: the wedged step owns the cv, so the
        # watchdog must never take it.  _stop/alive are monotonic flags and
        # a stale _step_t0 only delays the trip by one tick.
        while not self._stop and self.alive:  # graftlint: disable=concurrency
            t0 = self._step_t0                # graftlint: disable=concurrency
            if t0 is not None and time.monotonic() - t0 > timeout:
                self._trip_stuck(time.monotonic() - t0)
                return
            time.sleep(tick)

    def _trip_stuck(self, elapsed):
        """Lock-free replica death for a wedged step (see ``_watch_steps``):
        everything ``_die`` does except touching the engine, which stays
        owned by the stuck step thread.  The cv-free error/alive writes are
        the point — taking the cv here would deadlock on the stuck step —
        hence the concurrency pragmas."""
        self.error = StuckStepError(  # graftlint: disable=concurrency
            f"replica {self.name!r} step exceeded step_wall_timeout="
            f"{self.step_wall_timeout}s (ran {elapsed:.2f}s)")
        self.alive = False            # graftlint: disable=concurrency
        _obs.FRONTEND_STUCK_STEPS.inc(replica=self.name)
        if self.router is not None:
            self.router.forget(self.name)
        with self._out_cv:
            for slot in self._out.values():
                if not slot["status"].terminal:
                    slot["status"] = _RequestStatus.FAILED
                    if slot.get("trace") is not None:
                        # recorder lock is a leaf — safe from this cv-free
                        # context; the victim's post-mortem survives ring
                        # churn (and dumps when a dump dir is configured)
                        _flight.pin(slot["trace"], "stuck_step")
            self._out_cv.notify_all()

    def _publish(self):
        """Move every tracked request's new tokens and current status from
        the engine into the outbox and wake pollers.  Caller holds the
        engine condition; terminal slots are already complete and skipped.
        Terminal slots are retained (a drained slot is a status enum and an
        empty list) so re-polls of a finished rid stay answerable — the
        engine keeps its own finished table just the same."""
        eng = self.engine
        with self._out_cv:
            changed = False
            for rid, slot in self._out.items():
                if slot["status"].terminal:
                    continue
                toks = eng.new_tokens(rid)
                status = eng.status(rid)
                if toks:
                    slot["toks"].extend(int(t) for t in toks)
                    changed = True
                if status is not slot["status"]:
                    slot["status"] = status
                    changed = True
            if changed:
                self._out_cv.notify_all()

    def _die(self, error):
        """Step loop died: fail every inflight request with a typed terminal
        status, drop our prefix mirror, and stop accepting work.  Caller
        holds the condition."""
        self.alive = False
        self.error = error
        try:
            self.engine.fail_all(error)
        finally:
            if self.router is not None:
                self.router.forget(self.name)
            self._publish()
            self._cv.notify_all()

    # ---- request facade ------------------------------------------------------
    def load(self):
        """Scheduling pressure: waiting + active requests (the router's
        tie-breaker and the least-loaded fallback metric)."""
        with self._cv:
            eng = self.engine
            return len(eng._waiting) + sum(
                1 for s in eng._slots if s is not None)

    def submit(self, prompt_ids, **kw):
        """Thread-safe ``add_request``; wakes the step loop.  The returned
        rid may already be terminal SHED (engine-level admission)."""
        with self._cv:
            if not self.alive:
                raise ReplicaDeadError(
                    f"replica {self.name!r} is dead: {self.error!r}")
            rid = self.engine.add_request(prompt_ids, **kw)
            ctx = _flight.current()
            with self._out_cv:
                # remember the trace so lock-free anomaly paths (the stuck-
                # step watchdog) can pin it without touching the engine
                self._out[rid] = {"toks": [],
                                  "status": self.engine.status(rid),
                                  "trace": None if ctx is None
                                  else ctx.trace_id}
            self._cv.notify_all()
            return rid

    def poll(self, rid, timeout=None):
        """Block until ``rid`` has new tokens or is terminal; returns
        ``(tokens, status)``.  ``timeout`` bounds the WHOLE wait — the wait
        happens on the outbox condition, which is never held across an
        engine step, so a multi-second step (first-call jit compile, a
        fault-paced slow step) cannot stall a timed poll past its deadline
        and SSE heartbeats keep flowing.  On expiry the current (possibly
        empty) increment is returned with the last published status."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._out_cv:
            while True:
                slot = self._out.get(rid)
                if slot is None:
                    break  # not submitted through this facade
                toks, status = slot["toks"], slot["status"]
                if toks or status.terminal:
                    slot["toks"] = []
                    return toks, status
                if deadline is None:
                    self._out_cv.wait(self._poll)
                else:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return [], status
                    self._out_cv.wait(min(left, self._poll))
        # fallback for rids the engine was handed directly: read under the
        # engine condition (may block for a step; such callers own the
        # engine's pace anyway)
        with self._cv:
            while True:
                toks = self.engine.new_tokens(rid)
                status = self.engine.status(rid)
                if toks or status.terminal:
                    return toks, status
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return [], status
                    self._cv.wait(min(left, self._poll))
                else:
                    self._cv.wait(self._poll)

    def cancel(self, rid):
        with self._cv:
            ok = self.engine.cancel(rid)
            self._publish()
            self._cv.notify_all()
            return ok

    def status(self, rid):
        with self._cv:
            return self.engine.status(rid)

    def result(self, rid):
        with self._cv:
            return list(self.engine.result(rid))

    def request_error(self, rid):
        with self._cv:
            return self.engine.error(rid)

    def ttft(self, rid):
        with self._cv:
            try:
                return self.engine.ttft(rid)
            except KeyError:
                return None

    def tpot(self, rid):
        with self._cv:
            try:
                return self.engine.tpot(rid)
            except KeyError:
                return None

    def prefix_keys(self):
        """Chain keys resident in this replica's prefix cache — the fleet
        layer snapshots these over RPC to warm the gateway-side router for
        replicas whose cache events never cross the process boundary."""
        with self._cv:
            fn = getattr(self.engine, "prefix_keys", None)
            return list(fn()) if fn is not None else []

    def export_pages(self, keys):
        """Serve a peer's page pull: the longest prefix of ``keys`` this
        replica's engine holds in any KV tier, as a dense host block (None
        on a full miss or an engine without the tier API)."""
        with self._cv:
            if not self.alive:
                raise ReplicaDeadError(
                    f"replica {self.name!r} is dead: {self.error!r}")
            fn = getattr(self.engine, "export_pages", None)
            return fn(keys) if fn is not None else None

    def import_pages(self, payload):
        """Splice a peer's exported page block into this replica's engine
        (0 when the engine lacks the tier API)."""
        with self._cv:
            if not self.alive:
                raise ReplicaDeadError(
                    f"replica {self.name!r} is dead: {self.error!r}")
            fn = getattr(self.engine, "import_pages", None)
            return fn(payload) if fn is not None else 0

    def health(self):
        with self._cv:
            h = self.engine.health()
            # read alive/error under the cv too: the snapshot then can't
            # pair a pre-death engine view with a post-death error
            h["alive"] = self.alive
            h["error"] = repr(self.error) if self.error is not None else None
        h["replica"] = self.name
        return h

    def metrics(self):
        with self._cv:
            return self.engine.metrics()


class RequestHandle:
    """Where a routed request lives: the replica, its rid there, and the
    submit timestamp the stream-duration histogram measures from.

    For crash recovery the handle also remembers what was submitted
    (``prompt_ids`` / ``kw``) and every token already delivered to the
    caller (``emitted`` — ``streamed`` is its length).  A replica death
    with ``streamed == 0`` may be transparently resubmitted elsewhere
    (``requeued``, once); one that already streamed tokens may be RESUMED
    once (``resumed``) — resubmitted with ``emitted`` as re-prefill
    context so the continuation is token-exact.  Only when recovery itself
    fails does the handle pin a typed terminal via ``final_status`` /
    ``final_error``.  ``resume_t0`` stamps the death-detection instant so
    the first post-resume token lands in the splice-latency histogram."""

    __slots__ = ("replica", "rid", "t0", "_accounted", "prompt_ids", "kw",
                 "emitted", "requeued", "resumed", "resume_t0",
                 "final_status", "final_error", "trace_id")

    def __init__(self, replica, rid, prompt_ids=None, kw=None):
        self.replica = replica
        self.rid = rid
        self.trace_id = None         # flight-recorder trace (ambient ctx)
        self.t0 = time.perf_counter()
        self._accounted = False
        self.prompt_ids = prompt_ids
        self.kw = kw or {}
        self.emitted = []
        self.requeued = False
        self.resumed = False
        self.resume_t0 = None
        self.final_status = None
        self.final_error = None

    @property
    def streamed(self):
        """Tokens already delivered to the caller."""
        return len(self.emitted)

    def __repr__(self):
        return f"RequestHandle({self.replica.name!r}, rid={self.rid})"


class ReplicaSet:
    """N replicas behind one submit/stream/cancel facade.

    ``engines`` may be constructed engines or a list of (name, engine)
    pairs; default names are ``r0..rN-1``.  The default router is
    :class:`~.router.PrefixAffinityRouter` fed by every replica's cache
    events; pass ``router=RoundRobinRouter()`` for the affinity-blind
    baseline.  ``admission`` is consulted before routing — a refusal raises
    :class:`~.admission.ShedError` without touching any replica.

    ``requeue=True`` turns on crash recovery: when a replica dies under an
    inflight request that has streamed ZERO tokens, the request is
    transparently resubmitted once onto a surviving replica (routed warm
    through the prefix-affinity router).  A request that already streamed
    tokens is RESUMED once instead: resubmitted with its emitted history as
    ``resume_tokens`` — the survivor re-prefills prompt + history (cheap
    when prefix-cache pages are warm) and continues decode token-exact, so
    the caller's stream splices seamlessly with no duplicated or dropped
    tokens.  A partially-streamed request fails typed FAILED only when its
    single resume attempt also dies.  The multi-process fleet enables this —
    the in-process default stays off, preserving fail-fast semantics.
    """

    def __init__(self, engines, router=None, admission=None, names=None,
                 start=True, poll_interval=0.05, requeue=False,
                 step_wall_timeout=None, peer_pull=False,
                 peer_pull_min_pages=1):
        engines = list(engines)
        if not engines:
            raise ValueError("ReplicaSet needs at least one engine")
        if engines and isinstance(engines[0], tuple):
            names = [n for n, _ in engines]
            engines = [e for _, e in engines]
        if names is None:
            names = [f"r{i}" for i in range(len(engines))]
        if router is None:
            router = PrefixAffinityRouter(page_size=engines[0].page)
        self.router = router
        self.admission = admission if admission is not None else AlwaysAdmit()
        self.requeue = bool(requeue)
        # peer KV tier: when routing passes over a deeper-overlap holder
        # (router max_load_skew), cold-pull its page chain into the chosen
        # replica before submit.  Off by default — the pull is pure warmth,
        # never correctness, and extra RPCs would perturb seeded chaos
        # schedules that count rpc.* fault ordinals.
        self._peer_pull = bool(peer_pull)
        self._peer_pull_min = int(peer_pull_min_pages)
        self._pull_retry = RetryPolicy(max_attempts=3, base_delay=0.01,
                                       max_delay=0.25)
        self.replicas = [
            EngineReplica(n, e, router=router, poll_interval=poll_interval,
                          step_wall_timeout=step_wall_timeout)
            for n, e in zip(names, engines)]
        self._by_name = {r.name: r for r in self.replicas}
        if start:
            self.start()

    # ---- lifecycle -----------------------------------------------------------
    def start(self):
        for r in self.replicas:
            r.start()
        return self

    def close(self):
        for r in self.replicas:
            r.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def replica(self, name):
        return self._by_name[name]

    def alive_replicas(self):
        return [r for r in self.replicas if r.alive]

    def add_replica(self, replica, start=False):
        """Join a pre-built replica (in-process or remote) into routing;
        replaces any previous replica of the same name."""
        old = self._by_name.get(replica.name)
        if old is not None:
            self.remove_replica(old.name)
        self.replicas.append(replica)
        self._by_name[replica.name] = replica
        if start and hasattr(replica, "start"):
            replica.start()
        return replica

    def remove_replica(self, name):
        """Drop a replica from routing (its inflight handles hit the death
        path on their next poll); returns the removed replica or None."""
        rep = self._by_name.pop(name, None)
        if rep is not None:
            self.replicas.remove(rep)
            self.router.forget(name)
        return rep

    # ---- request facade ------------------------------------------------------
    def submit(self, prompt_ids, **kw):
        """Admit, route, and submit one request; returns a
        :class:`RequestHandle`.  Raises :class:`~.admission.ShedError` on
        admission refusal and :class:`ReplicaDeadError` with no live
        replicas."""
        _faults.FAULTS.maybe_fire("frontend.route")
        alive = self.alive_replicas()
        if not alive:
            raise ReplicaDeadError("no live replicas")
        decision = self.admission.decide(alive)
        if not decision.admit:
            _obs.FRONTEND_SHED.inc(reason=decision.reason)
            _obs.FRONTEND_REQUESTS.inc(outcome="shed")
            raise ShedError(decision.reason, decision.retry_after)
        # a replica can die between routing and submit (remote worker
        # killed); reroute over the survivors instead of failing the request
        tried = set()
        while True:
            candidates = [r for r in self.alive_replicas()
                          if r.name not in tried]
            if not candidates:
                raise ReplicaDeadError("no live replicas")
            route = self.router.route(prompt_ids, candidates)
            rep = route.replica
            if self._peer_pull and route.holder is not None \
                    and route.holder is not rep \
                    and route.holder_overlap - route.overlap \
                    >= self._peer_pull_min:
                # warm the chosen replica with the passed-over holder's
                # pages BEFORE submit, so admission sees them as hits
                self._peer_warm(rep, route.holder, prompt_ids,
                                route.overlap, route.holder_overlap)
            _faults.FAULTS.maybe_fire("frontend.submit", replica=rep.name)
            try:
                rid = rep.submit(prompt_ids, **kw)
                break
            except ReplicaDeadError:
                tried.add(rep.name)
        if rep.status(rid) is _RequestStatus.SHED:
            # the engine's own admission control refused it (queue bound /
            # page watermark); surface it exactly like a frontend shed
            _obs.FRONTEND_SHED.inc(reason="engine")
            _obs.FRONTEND_REQUESTS.inc(outcome="shed")
            raise ShedError("engine", decision.retry_after)
        _obs.FRONTEND_ROUTED.inc(replica=rep.name, reason=route.reason)
        _obs.FRONTEND_INFLIGHT.inc()
        handle = RequestHandle(rep, rid, prompt_ids=list(prompt_ids),
                               kw=dict(kw))
        ctx = _flight.current()
        if ctx is not None:
            handle.trace_id = ctx.trace_id
            _flight.record("routed", rid=rid, trace_id=ctx.trace_id,
                           replica=rep.name, reason=route.reason)
        return handle

    def _peer_warm(self, rep, holder, prompt_ids, lo, hi):
        """Cold-pull the passed-over holder's cached page chain
        ``[lo, hi)`` into the chosen replica before submit — the peer tier
        of the KV hierarchy.  Strictly best-effort: a miss (the holder aged
        the chain out), a dead peer, or a ``kv.peer_pull`` fault all fall
        back to recompute; the request is submitted regardless and its
        tokens are identical either way — only prefill work changes."""
        page = getattr(self.router, "page", None)
        if page is None:
            return
        keys = prefix_page_keys(prompt_ids, page)[lo:hi]
        if not keys:
            return

        def attempt():
            try:
                _faults.FAULTS.maybe_fire(
                    "kv.peer_pull", replica=rep.name, holder=holder.name)
                return holder.export_pages(keys)
            except Exception as err:
                if getattr(err, "transient", False):
                    raise _TransientPull(err) from err
                raise

        try:
            payload = retry_call(attempt, policy=self._pull_retry,
                                 retry_on=(_TransientPull,),
                                 op="kv.peer_pull")
            n = rep.import_pages(payload) if payload else 0
        except Exception:  # noqa: BLE001 — recompute fallback
            _obs.FRONTEND_PEER_PULLS.inc(outcome="failed")
            return
        _obs.FRONTEND_PEER_PULLS.inc(outcome="ok" if n else "miss")

    def _account(self, handle, status):
        """First terminal observation of a request: outcome counter, inflight
        gauge, stream-duration histogram, and the admission policy's TTFT and
        TPOT windows.  Idempotent per handle."""
        if handle._accounted:
            return
        handle._accounted = True
        _obs.FRONTEND_REQUESTS.inc(outcome=status.value)
        _obs.FRONTEND_INFLIGHT.inc(-1)
        _obs.FRONTEND_STREAM_SECONDS.observe(time.perf_counter() - handle.t0)
        try:
            self.admission.observe_ttft(handle.replica.ttft(handle.rid))
            observe_tpot = getattr(self.admission, "observe_tpot", None)
            if observe_tpot is not None:
                observe_tpot(handle.replica.tpot(handle.rid))
        except ReplicaDeadError:
            pass  # the replica died under us; its latencies died with it

    # ---- replica-death handling ---------------------------------------------
    def _poll_handle(self, handle, timeout):
        """``replica.poll`` with fleet-level crash recovery: a dead replica
        requeues the handle (zero tokens streamed), resumes it with its
        emitted history (partially streamed), or — when recovery itself is
        impossible — pins a typed FAILED terminal on it."""
        if handle.final_status is not None:
            return [], handle.final_status
        try:
            toks, status = handle.replica.poll(handle.rid, timeout=timeout)
        except ReplicaDeadError as e:
            return [], self._on_replica_death(handle, e)
        if (status is _RequestStatus.FAILED and self.requeue
                and not getattr(handle.replica, "alive", True)):
            # in-process replica death: the step loop's fail_all pinned
            # FAILED instead of raising on poll.  Tokens the dying step
            # decoded but never delivered are dropped here — the resume
            # regenerates them (greedy/fixed-seed tokens are pure functions
            # of context), so the caller's stream stays gap-free.
            return [], self._on_replica_death(handle, ReplicaDeadError(
                f"replica {handle.replica.name!r} died mid-request: "
                f"{handle.replica.error!r}"))
        handle.emitted.extend(int(t) for t in toks)
        if toks and handle.resume_t0 is not None:
            _obs.FRONTEND_SPLICE_SECONDS.observe(
                time.perf_counter() - handle.resume_t0)
            handle.resume_t0 = None
        return toks, status

    def _on_replica_death(self, handle, error):
        """The replica under ``handle`` died (lease expiry / RPC failure /
        in-process step death).  Zero-streamed requests are requeued once;
        partially-streamed ones are resumed once with their emitted history
        as re-prefill context (token-exact continuation).  Returns the
        handle's new status: a live one after successful recovery, else the
        pinned terminal."""
        if self.requeue and handle.prompt_ids is not None:
            if handle.streamed == 0 and not handle.requeued:
                try:
                    alive = [r for r in self.alive_replicas()
                             if r is not handle.replica]
                    if alive:
                        route = self.router.route(handle.prompt_ids, alive)
                        # resubmit under the original trace so the survivor's
                        # engine spans join the caller's request timeline
                        rctx = (None if handle.trace_id is None
                                else _flight.mint(handle.trace_id))
                        with _flight.use_context(rctx):
                            rid = route.replica.submit(handle.prompt_ids,
                                                       **handle.kw)
                        if route.replica.status(rid) \
                                is not _RequestStatus.SHED:
                            handle.replica, handle.rid = route.replica, rid
                            handle.requeued = True
                            if handle.trace_id is not None:
                                _flight.record("requeue", rid=rid,
                                               trace_id=handle.trace_id,
                                               replica=route.replica.name)
                            _obs.FRONTEND_REQUEUED.inc()
                            _obs.FRONTEND_ROUTED.inc(
                                replica=route.replica.name, reason="requeue")
                            return route.replica.status(rid)
                except (ReplicaDeadError, ShedError):
                    pass  # no survivor could take it: fall through to FAILED
            elif handle.streamed > 0 and not handle.resumed:
                status = self._resume(handle)
                if status is not None:
                    return status
        handle.final_status = _RequestStatus.FAILED
        handle.final_error = error
        self._account(handle, _RequestStatus.FAILED)
        return _RequestStatus.FAILED

    def _resume(self, handle):
        """One attempt to continue a partially-streamed ``handle`` on a
        survivor: resubmit with ``emitted`` as ``resume_tokens`` (the
        engine re-prefills prompt + history, cheap when prefix-cache pages
        are warm) and the REMAINING token budget.  Returns the resumed
        request's live status, a locally-pinned terminal when the dead
        replica owed nothing but the final status, or None when the attempt
        failed (the caller pins FAILED)."""
        handle.resumed = True
        t_death = time.perf_counter()
        emitted = list(handle.emitted)
        kw = dict(handle.kw)
        remaining = int(kw.get("max_new_tokens", 16)) - len(emitted)
        eos = kw.get("eos_token_id")
        hit_eos = eos is not None and emitted[-1] == eos
        if remaining <= 0 or hit_eos:
            # the caller already holds the complete output; only the
            # terminal status died with the replica — pin it locally
            status = (_RequestStatus.EOS if hit_eos
                      else _RequestStatus.FINISHED)
            handle.final_status = status
            self._account(handle, status)
            return status
        kw["max_new_tokens"] = remaining
        # a request already driven with resume_tokens (gateway crash
        # recovery) must carry its FULL history — prior resume prefix plus
        # what this incarnation streamed — or the re-prefill would forget
        # the pre-recovery tokens
        kw["resume_tokens"] = list(kw.get("resume_tokens") or []) + emitted
        try:
            _faults.FAULTS.maybe_fire("frontend.resume",
                                      replica=handle.replica.name)
            alive = [r for r in self.alive_replicas()
                     if r is not handle.replica]
            if not alive:
                return None
            # route by prompt + history: the survivor holding the warmest
            # prefix pages re-prefills the least
            route = self.router.route(list(handle.prompt_ids) + emitted,
                                      alive)
            # the resumed incarnation stays on the ORIGINAL trace — one
            # merged timeline shows death, splice, and continuation
            rctx = (None if handle.trace_id is None
                    else _flight.mint(handle.trace_id))
            with _flight.use_context(rctx):
                rid = route.replica.submit(handle.prompt_ids, **kw)
            if route.replica.status(rid) is _RequestStatus.SHED:
                return None
        except (ReplicaDeadError, ShedError, _faults.InjectedFault):
            return None  # the resume attempt itself died: caller pins FAILED
        handle.replica, handle.rid = route.replica, rid
        handle.resume_t0 = t_death
        if handle.trace_id is not None:
            _flight.record("resume", rid=rid, trace_id=handle.trace_id,
                           replica=route.replica.name,
                           streamed=len(emitted))
            _flight.pin(handle.trace_id, "resume")
        _obs.FRONTEND_RESUMED.inc()
        _obs.FRONTEND_ROUTED.inc(replica=route.replica.name, reason="resume")
        return route.replica.status(rid)

    def stream_batches(self, handle, poll_timeout=0.5, heartbeat=None):
        """Yield ``(tokens, status)`` batches for ``handle`` — each batch
        exactly as one poll delivered it — until the request is terminal.
        This is the primitive the durable request plane journals from: a
        batch boundary here is a journal-record boundary there.

        ``heartbeat`` (seconds): when set, an EMPTY batch ``([], status)``
        is yielded whenever that long passes without a token — the liveness
        signal :meth:`stream` turns into its ``None`` pings."""
        last = time.monotonic()
        slice_ = (poll_timeout if heartbeat is None
                  else min(poll_timeout, float(heartbeat)))
        while True:
            toks, status = self._poll_handle(handle, slice_)
            if toks:
                yield list(toks), status
                last = time.monotonic()
            elif (heartbeat is not None and not status.terminal
                    and time.monotonic() - last >= float(heartbeat)):
                yield [], status
                last = time.monotonic()
            if status.terminal and not toks:
                # drain once more: tokens emitted by the finalizing step
                # land before the terminal status is visible.  The terminal
                # status is already in hand, so a replica dying exactly here
                # has nothing left to deliver — never trigger recovery (a
                # resume now could regenerate a completed request).
                if handle.final_status is None:
                    try:
                        tail, _ = handle.replica.poll(handle.rid, timeout=0)
                    except ReplicaDeadError:
                        tail = []
                    handle.emitted.extend(int(t) for t in tail)
                    if tail:
                        yield list(tail), status
                self._account(handle, status)
                return

    def stream(self, handle, poll_timeout=0.5, heartbeat=None):
        """Yield ``handle``'s tokens as they are emitted, one int at a time,
        until the request is terminal.  Check ``self.status(handle)`` after
        exhaustion for the terminal status.

        ``heartbeat`` (seconds): when set, the generator yields ``None``
        whenever that long passes without a token — long prefill or queue
        waits stay observably alive.  The SSE gateway turns each ``None``
        into a ``: ping`` keep-alive comment, whose failing write is also
        how a client that disconnected before the first token is detected.
        """
        for toks, _status in self.stream_batches(handle, poll_timeout,
                                                 heartbeat):
            if not toks:
                yield None
            else:
                yield from toks

    def result(self, handle, timeout=None):
        """Block until terminal; returns ``(tokens, status)``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            _, status = self._poll_handle(handle, 1.0)
            if status.terminal:
                self._account(handle, status)
                if handle.final_status is _RequestStatus.FAILED:
                    return [], handle.final_status
                if handle.final_status is not None or handle.resumed:
                    # locally-pinned terminal, or a resumed request whose
                    # replica-side result holds only the post-splice tail:
                    # ``emitted`` is the complete drained stream
                    return list(handle.emitted), status
                return handle.replica.result(handle.rid), status
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"{handle!r} not terminal after {timeout}s")

    def status(self, handle):
        if handle.final_status is not None:
            return handle.final_status
        try:
            return handle.replica.status(handle.rid)
        except ReplicaDeadError as e:
            return self._on_replica_death(handle, e)

    def cancel(self, handle):
        if handle.final_status is not None:
            return False
        try:
            return handle.replica.cancel(handle.rid)
        except ReplicaDeadError:
            return False

    def request_error(self, handle):
        if handle.final_error is not None:
            return repr(handle.final_error)
        try:
            return handle.replica.request_error(handle.rid)
        except ReplicaDeadError as e:
            return repr(e)

    def health(self):
        """Per-replica health snapshots keyed by replica name."""
        return {r.name: r.health() for r in self.replicas}

    def metrics(self):
        """Per-replica registry snapshots keyed by replica name."""
        return {r.name: r.metrics() for r in self.replicas}

    # ---- fleet observability -------------------------------------------------
    def _federation_members(self, attr):
        """``(name, bound scrape method)`` for every live member that runs
        in its OWN process and exposes ``attr`` (in-process replicas share
        this registry/recorder and contribute through the local snapshot).
        Members already known dead are skipped WITHOUT touching the error
        counter — their failure was counted once, when it was detected, and
        re-counting per /metrics scrape would turn the counter's rate into
        a dead-member clock — tallied instead in the
        ``frontend_federation_skipped`` gauge."""
        members, skipped = [], 0
        for rep in list(self.replicas):
            fn = getattr(rep, attr, None)
            if fn is None:
                continue  # in-process: already in the local snapshot
            if not getattr(rep, "alive", True):
                skipped += 1
                continue
            members.append((rep.name, fn))
        _obs.FRONTEND_FEDERATION_SKIPPED.set(skipped)
        return members

    @staticmethod
    def _scrape_fleet(jobs):
        """Run per-member scrape thunks CONCURRENTLY so the page's worst
        case is ~one deadline, not one deadline per member, and return
        {name: result} for the members that answered.  A thunk that raises
        (dead mid-scrape, wedged past its deadline) is dropped with
        ``frontend_federation_errors_total{replica=}`` incremented — a
        half-dead worker must never wedge the /metrics page."""
        if not jobs:
            return {}
        results = {}
        with ThreadPoolExecutor(max_workers=min(16, len(jobs)),
                                thread_name_prefix="fed-scrape") as pool:
            futures = {pool.submit(fn): name for name, fn in jobs.items()}
            for fut in as_completed(futures):
                name = futures[fut]
                try:
                    results[name] = fut.result()
                except Exception:  # noqa: BLE001 — scrape must never wedge
                    _obs.FRONTEND_FEDERATION_ERRORS.inc(replica=name)
        return results

    def federated_snapshot(self, deadline=1.0):
        """Full registry snapshots of every live own-process member (remote
        workers), keyed by replica name — the scrape half of metrics
        federation.  Dead-member and failure semantics per
        :meth:`_federation_members` / :meth:`_scrape_fleet`."""
        return self._scrape_fleet({
            name: functools.partial(fn, deadline=deadline)
            for name, fn in self._federation_members("metrics_snapshot")})

    def metrics_exposition(self, deadline=1.0):
        """One Prometheus page for the WHOLE fleet: this process's registry
        merged with every live remote member's snapshot, remote series
        relabeled ``replica=<name>``."""
        # scrape the remotes FIRST: a member that dies mid-scrape bumps the
        # federation error counter, and the local snapshot must be taken
        # after that so the very page that skipped it reports the skip
        remotes = self.federated_snapshot(deadline)
        return _obs.render_snapshot(_obs.merge_snapshots(
            _obs.REGISTRY.snapshot(), remotes))

    def trace_events_fleet(self, trace_id, deadline=1.0):
        """Every span event recorded for ``trace_id`` anywhere in the
        fleet — this process's flight recorder plus each live remote
        member's — merged, deduplicated, and causally ordered.  Dead or
        unresponsive members are skipped (same semantics as the metrics
        scrape)."""
        pulled = self._scrape_fleet({
            name: functools.partial(fn, trace_id, deadline=deadline)
            for name, fn in self._federation_members("trace_events")})
        return _flight.merge_events(_flight.snapshot_events(trace_id),
                                    *pulled.values())
