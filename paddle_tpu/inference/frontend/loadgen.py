"""Deterministic trace-driven load generation for the serving front door.

A trace is a plain list of request dicts built from one seed, so the test
suite and ``bench.py`` replay byte-identical workloads: ``make_trace`` draws
``groups`` shared prefixes (whole KV pages, to make prefix-cache affinity
visible) and gives every request its own suffix.  ``run_closed_loop`` drives
a :class:`~.replica.ReplicaSet` with N concurrency workers, each submitting
its next request only after the previous one is terminal (closed loop — the
offered load adapts to the service rate instead of piling an unbounded
queue), and ``summarize`` reduces the per-request records to the numbers the
bench reports: aggregate tokens/s and p50/p95 TTFT.
"""
from __future__ import annotations

import json
import random
import threading
import time
import urllib.request

__all__ = ["make_trace", "run_closed_loop", "summarize", "percentile",
           "http_completion"]


def make_trace(seed, n_requests, groups=4, prefix_pages=2, suffix_tokens=4,
               page_size=16, vocab=128, max_new_tokens=8, group_major=True):
    """Build a deterministic request trace with shared prefixes.

    ``groups`` distinct prefixes of ``prefix_pages`` full pages are drawn
    once; request i belongs to group ``i % groups`` (interleaved) or to
    block ``i // (n/groups)`` (``group_major=True`` — all of a group's
    requests are adjacent, the shape that separates affinity routing from
    round-robin).  Suffixes are unique per request so only the prefix can
    hit the cache."""
    rng = random.Random(int(seed))
    groups = max(1, int(groups))
    prefixes = [[rng.randrange(int(vocab)) for _ in
                 range(int(prefix_pages) * int(page_size))]
                for _ in range(groups)]
    trace = []
    for i in range(int(n_requests)):
        g = (i * groups // int(n_requests)) if group_major else (i % groups)
        suffix = [rng.randrange(int(vocab)) for _ in range(int(suffix_tokens))]
        trace.append({"prompt": prefixes[g] + suffix,
                      "max_tokens": int(max_new_tokens),
                      "group": g})
    return trace


def run_closed_loop(replica_set, trace, concurrency=4, submit_kw=None):
    """Drive ``replica_set`` with the trace at a fixed closed-loop
    concurrency; returns ``(records, wall_seconds)``.

    Each record: ``{"group", "replica", "status", "tokens", "ttft"}`` in
    trace order.  Sheds are recorded (status ``shed``, no tokens) and do not
    stop the worker."""
    from .admission import ShedError

    trace = list(trace)
    records = [None] * len(trace)
    cursor = {"i": 0}
    lock = threading.Lock()
    submit_kw = dict(submit_kw or {})

    def worker():
        while True:
            with lock:
                i = cursor["i"]
                if i >= len(trace):
                    return
                cursor["i"] = i + 1
            req = trace[i]
            try:
                handle = replica_set.submit(req["prompt"],
                                            max_new_tokens=req["max_tokens"],
                                            **submit_kw)
            except ShedError:
                records[i] = {"group": req["group"], "replica": None,
                              "status": "shed", "tokens": 0, "ttft": None}
                continue
            tokens, status = replica_set.result(handle)
            records[i] = {"group": req["group"],
                          "replica": handle.replica.name,
                          "status": status.value,
                          "tokens": len(tokens),
                          "ttft": handle.replica.ttft(handle.rid)}

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, name=f"loadgen-{k}",
                                daemon=True)
               for k in range(max(1, int(concurrency)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return records, time.perf_counter() - t0


def percentile(values, q):
    """Nearest-rank percentile (q in [0, 100]) of a non-empty sequence."""
    vals = sorted(values)
    if not vals:
        raise ValueError("percentile of empty sequence")
    k = max(0, min(len(vals) - 1,
                   round(q / 100.0 * (len(vals) - 1))))
    return vals[int(k)]


def summarize(records, wall_seconds):
    """Reduce closed-loop records to the bench-facing aggregate numbers."""
    done = [r for r in records if r is not None]
    ttfts = [r["ttft"] for r in done if r["ttft"] is not None]
    total_tokens = sum(r["tokens"] for r in done)
    return {
        "requests": len(done),
        "shed": sum(1 for r in done if r["status"] == "shed"),
        "failed": sum(1 for r in done if r["status"] == "failed"),
        "total_tokens": total_tokens,
        "wall_s": round(wall_seconds, 4),
        "tokens_per_s": round(total_tokens / wall_seconds, 2)
        if wall_seconds > 0 else 0.0,
        "ttft_p50_s": round(percentile(ttfts, 50), 4) if ttfts else None,
        "ttft_p95_s": round(percentile(ttfts, 95), 4) if ttfts else None,
    }


def http_completion(base_url, prompt, max_tokens=16, stream=False,
                    timeout=30.0, headers=None, **sampling):
    """One ``POST /v1/completions`` against a running gateway.

    Non-stream: returns the decoded JSON body.  Stream: consumes the SSE
    response and returns ``{"tokens": [...], "status": ..., "events": n,
    "last_id": ...}`` reassembled from the events — the shape tests compare
    against the engine-direct result.  ``last_id`` is the final ``id:``
    field seen (None on a non-durable gateway), ready to echo back as
    ``Last-Event-ID`` on a reconnect.  ``headers`` adds request headers —
    the durable gateway's ``Idempotency-Key`` / ``Last-Event-ID`` ride
    here."""
    body = {"prompt": [int(t) for t in prompt],
            "max_tokens": int(max_tokens), "stream": bool(stream)}
    body.update(sampling)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        base_url.rstrip("/") + "/v1/completions",
        data=json.dumps(body).encode("utf-8"),
        headers=hdrs, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        if not stream:
            return json.loads(resp.read().decode("utf-8"))
        tokens, status, events, last_id = [], None, 0, None
        for raw in resp:
            line = raw.decode("utf-8").strip()
            if line.startswith("id: "):
                last_id = int(line[len("id: "):])
                continue
            if not line.startswith("data: "):
                continue
            events += 1
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            evt = json.loads(payload)
            if "token" in evt:
                tokens.append(evt["token"])
            else:
                status = evt.get("status")
        return {"tokens": tokens, "status": status, "events": events,
                "last_id": last_id}
