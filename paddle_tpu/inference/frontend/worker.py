"""Replica worker: one engine in its own OS process, leased into the fleet.

A worker is the fleet's unit of failure isolation: it hosts one
``LLMEngine`` behind the same :class:`~.replica.EngineReplica` facade the
in-process front door uses, serves the replica ops over the
:mod:`~paddle_tpu.inference.frontend.rpc` channel, and holds a
:class:`~paddle_tpu.distributed.membership.Lease` whose heartbeat is the
worker's liveness signal — a crash (any kind, including ``kill -9``) stops
the renewals and the fleet expires the member one TTL later, while a
SIGTERM drains gracefully: stop admitting, finish inflight, release the
lease so watchers see ``leave`` immediately.

:class:`WorkerServer` is host-agnostic on purpose — production runs it
under ``python -m paddle_tpu.inference.frontend.worker`` as a supervised
child process, the deterministic tier-1 tests run several in threads of
one process with an injected clock, and the bench does the same to measure
degradation without TPU-sized process images.

RPC ops: ``submit poll cancel status result request_error ttft tpot load
health metrics metrics_snapshot trace_events prefix_keys pull_pages
push_pages ping``.  ``metrics_snapshot`` returns the worker process's FULL
metrics-registry snapshot (every family, not just the engine counters) for
gateway-side federation, and ``trace_events`` returns the flight recorder's
picklable span events — the pull half of fleet-wide request tracing.  ``pull_pages`` /
``push_pages`` are the peer KV tier's transfer halves: a gateway pulls a
serialized page-chain block out of the replica that holds it and pushes it
into the replica it routed to.  ``submit`` while draining raises
:class:`~.admission.ShedError` ("draining") so the gateway's shed path
handles the race between drain and route.

``role="prefill"`` turns the worker into a disaggregation prefill tier: a
:class:`~.disagg.PrefillHandoffBuffer` hooks the engine's
``prefill_sink``, the lease meta advertises the role, and four more ops
serve the handoff plane — ``handoff_ready handoff_pull handoff_cancel
handoff_audit`` (see :mod:`.disagg`).
"""
from __future__ import annotations

import os
import signal
import threading
import time

from ... import observability as _obs
from ...distributed.membership import MembershipService
from ...observability import flight as _flight
from .admission import ShedError
from .disagg import PrefillHandoffBuffer
from .replica import EngineReplica
from .rpc import RpcServer

__all__ = ["WorkerServer", "load_engine_factory", "main"]


class WorkerServer:
    """One leased engine replica served over RPC.

    ``store`` is a connected :class:`~paddle_tpu.distributed.store.TCPStore`
    client; the membership meta advertises ``host``/``port`` of the RPC
    endpoint (plus ``pid``), which is all a gateway needs to build a remote
    replica handle.
    """

    def __init__(self, name, engine, store, group="fleet", ttl=2.0,
                 host="127.0.0.1", port=0, clock=time.monotonic,
                 heartbeat_interval=None, retry_policy=None,
                 poll_interval=0.05, role="serve"):
        self.name = str(name)
        self.role = str(role)
        self.handoff = (PrefillHandoffBuffer(engine)
                        if self.role == "prefill" else None)
        self.replica = EngineReplica(self.name, engine,
                                     poll_interval=poll_interval)
        self.rpc = RpcServer(self._handle, host, port)
        self.membership = MembershipService(store, group=group, ttl=ttl,
                                            clock=clock,
                                            retry_policy=retry_policy)
        self.lease = None
        self.lease_lost = None
        self.draining = False
        self._hb_interval = heartbeat_interval
        self._poll = float(poll_interval)

    # ---- lifecycle -----------------------------------------------------------
    def start(self, heartbeat=True):
        """Start the engine loop + RPC listener, then register the lease.
        ``heartbeat=False`` leaves renewal to the caller (deterministic
        tests drive :meth:`Lease.renew` by hand)."""
        self.replica.start()
        self.rpc.start()
        self.lease = self.membership.register(self.name, meta={
            "host": self.rpc.host, "port": self.rpc.port,
            "pid": os.getpid(), "role": self.role})
        if heartbeat:
            self.lease.start_heartbeat(self._hb_interval,
                                       on_lost=self._on_lease_lost)
        return self

    def _on_lease_lost(self, error):
        # the fleet has (or will) expire us; remember why for health()
        self.lease_lost = error

    def drain(self, timeout=30.0):
        """Graceful drain: refuse new submits, wait for inflight work to
        finish (bounded by ``timeout``), release the lease."""
        self.draining = True
        deadline = time.monotonic() + float(timeout)
        while (self.replica.alive and self.replica.load() > 0
               and time.monotonic() < deadline):
            time.sleep(self._poll)
        if self.lease is not None:
            self.lease.release()

    def close(self, drain=True, drain_timeout=30.0):
        if drain:
            self.drain(drain_timeout)
        elif self.lease is not None:
            self.lease.release()
        self.rpc.close()
        self.replica.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- RPC dispatch --------------------------------------------------------
    def _handle(self, op, kw):
        # RPC connection threads vary per call: label each so worker-side
        # span events (queued, routed-to-us submits) name this worker
        _flight.set_proc_label(f"worker:{self.name}")
        rep = self.replica
        if op == "submit":
            if self.draining:
                raise ShedError("draining", retry_after=1.0)
            return rep.submit(kw.pop("prompt_ids"), **kw)
        if op == "poll":
            return rep.poll(kw["rid"], timeout=kw.get("timeout"))
        if op == "cancel":
            return rep.cancel(kw["rid"])
        if op == "status":
            return rep.status(kw["rid"])
        if op == "result":
            return rep.result(kw["rid"])
        if op == "request_error":
            return rep.request_error(kw["rid"])
        if op == "ttft":
            return rep.ttft(kw["rid"])
        if op == "tpot":
            return rep.tpot(kw["rid"])
        if op == "load":
            return rep.load()
        if op == "health":
            h = rep.health()
            h["draining"] = self.draining
            h["epoch"] = self.lease.epoch if self.lease else None
            h["lease_lost"] = (repr(self.lease_lost)
                               if self.lease_lost else None)
            return h
        if op == "metrics":
            return rep.metrics()
        if op == "metrics_snapshot":
            # the WHOLE process registry (engine + frontend + durable-plane
            # families), not just the engine's counters: the gateway merges
            # this under a replica= label for the federated /metrics page
            return _obs.REGISTRY.snapshot()
        if op == "trace_events":
            return _flight.snapshot_events(kw.get("trace_id"))
        if op == "prefix_keys":
            return rep.prefix_keys()
        if op == "pull_pages":
            return rep.export_pages(kw["keys"])
        if op == "push_pages":
            return rep.import_pages(kw["payload"])
        if op == "handoff_ready":
            return self.handoff.ready() if self.handoff is not None else []
        if op == "handoff_pull":
            if self.handoff is None:
                raise ValueError(
                    f"worker {self.name!r} has role={self.role!r}, not a "
                    "prefill tier")
            return self.handoff.pull(kw["rid"])
        if op == "handoff_cancel":
            if self.handoff is not None and self.handoff.drop(kw["rid"]):
                return True
            return rep.cancel(kw["rid"])
        if op == "handoff_audit":
            return self.audit_pages()
        # liveness probe for operators and the fleet tests — the gateway
        # itself never calls it, so CT101 sees no site in paddle_tpu/
        if op == "ping":  # graftlint: disable=contracts
            return {"name": self.name,
                    "epoch": self.lease.epoch if self.lease else None,
                    "pid": os.getpid()}
        raise ValueError(f"unknown worker op {op!r}")

    def audit_pages(self):
        """Page-refcount audit of the hosted engine, under the replica's
        engine condition — the worker-side half of a disaggregation pool's
        combined dual-pool audit (empty list means clean)."""
        rep = self.replica
        with rep._cv:
            eng = rep.engine
            fn = getattr(eng, "audit_refcounts", None)
            if fn is not None:
                return list(fn())
            return list(eng.pool.audit(
                eng.sched.expected_refs(eng.n_pages)))


def load_engine_factory(spec):
    """Resolve ``--engine-spec``: ``pkg.module:attr`` or ``/path/file.py:attr``
    (attr defaults to ``make_engine``).  The factory is called with no
    arguments and must return a constructed ``LLMEngine``."""
    path, _, attr = str(spec).partition(":")
    attr = attr or "make_engine"
    if path.endswith(".py"):
        import importlib.util
        modspec = importlib.util.spec_from_file_location("_worker_engine",
                                                         path)
        mod = importlib.util.module_from_spec(modspec)
        modspec.loader.exec_module(mod)
    else:
        import importlib
        mod = importlib.import_module(path)
    return getattr(mod, attr)


def main(argv=None):
    """``python -m paddle_tpu.inference.frontend.worker`` — the supervised
    child-process entry.  Blocks until SIGTERM (graceful drain) or death."""
    import argparse

    from ...distributed.store import TCPStore

    p = argparse.ArgumentParser(description="paddle-tpu fleet worker")
    p.add_argument("--engine-spec", required=True,
                   help="module:attr or file.py:attr engine factory")
    p.add_argument("--name", required=True)
    p.add_argument("--store-host", default="127.0.0.1")
    p.add_argument("--store-port", type=int, required=True)
    p.add_argument("--group", default="fleet")
    p.add_argument("--ttl", type=float, default=2.0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--heartbeat-interval", type=float, default=None)
    p.add_argument("--drain-timeout", type=float, default=30.0)
    p.add_argument("--role", default="serve", choices=("serve", "prefill"),
                   help="'prefill' parks finished prefills for a "
                        "disaggregation pool instead of decoding")
    args = p.parse_args(argv)

    engine = load_engine_factory(args.engine_spec)()
    store = TCPStore(host=args.store_host, port=args.store_port)
    server = WorkerServer(args.name, engine, store, group=args.group,
                          ttl=args.ttl, host=args.host, port=args.port,
                          heartbeat_interval=args.heartbeat_interval,
                          role=args.role)
    server.start()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.close(drain=True, drain_timeout=args.drain_timeout)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
