"""Multi-process fleet: membership-driven routing over remote workers.

The in-process :class:`~.replica.ReplicaSet` discovers replica death by
sharing an address space; the fleet cannot, so it listens to the
:mod:`~paddle_tpu.distributed.membership` plane instead.
:class:`FleetReplicaSet` keeps the whole ReplicaSet facade (submit /
stream / result / cancel / health — the gateway is unchanged) but its
replica list is a **fold over membership events**:

- ``join``  → build a :class:`RemoteReplica` from the member's advertised
  ``host``/``port`` meta and warm the prefix-affinity router with the
  worker's resident cache keys (``prefix_keys`` RPC) — a respawned worker
  (same name, new epoch) transparently replaces its dead incarnation.
- ``leave`` → clean drain: drop from routing (inflight work finished
  before the worker released its lease).
- ``expire`` → the worker stopped heartbeating (crash / wedge / kill -9):
  mark the replica dead so every inflight poll takes the crash-recovery
  path, then drop it from routing.

Crash recovery itself lives in the base class (``requeue=True`` here by
default): a request that has streamed zero tokens is resubmitted once onto
a surviving replica; one that already streamed tokens is resumed once —
resubmitted with its emitted history as ``resume_tokens`` so the survivor
re-prefills prompt + history and continues token-exact.  Either way only a
second death fails the request typed FAILED.

``sync()`` is one deterministic membership tick (tests drive it with a
fake clock); ``start_sync()`` wraps it in a daemon thread for wall-clock
deployments.

The durable request plane (:mod:`.journal`) pumps fleet requests through
the inherited :meth:`~.replica.ReplicaSet.stream_batches` — token batches
journal gateway-side before clients see them, so the fleet needs no
journal awareness of its own: worker processes stay stateless across
gateway restarts and the journal replay re-drives onto whichever workers
membership currently routes.
"""
from __future__ import annotations

import threading
import time

from ...core.retry import RetryPolicy
from ...distributed.membership import EXPIRE, JOIN, MembershipService
from ...observability import flight as _flight
from ...testing.faults import InjectedFault as _InjectedFault
from .admission import AlwaysAdmit
from .disagg import RemotePrefillTier
from .replica import ReplicaDeadError, ReplicaSet
from .router import PrefixAffinityRouter
from .rpc import RpcClient, RpcError

__all__ = ["RemoteReplica", "FleetReplicaSet"]


class RemoteReplica:
    """The :class:`~.replica.EngineReplica` facade over a worker's RPC
    endpoint.  Any channel failure (or an injected ``rpc.*`` fault) marks
    the replica dead and raises :class:`~.replica.ReplicaDeadError` — the
    fleet's requeue path takes it from there."""

    def __init__(self, name, host, port, epoch=None, connect_timeout=5.0):
        self.name = str(name)
        self.epoch = epoch
        self.client = RpcClient(host, port, connect_timeout=connect_timeout)
        self.alive = True
        self.error = None

    def _call(self, op, deadline=None, **kw):
        if not self.alive:
            raise ReplicaDeadError(
                f"replica {self.name!r} is dead: {self.error!r}")
        try:
            # thread the ambient trace through every frame: the worker's
            # span events join the caller's timeline with adopted Lamport
            # stamps (wire_context is None for untraced / disabled calls)
            return self.client.call(op, deadline=deadline,
                                    ctx=_flight.wire_context(), **kw)
        except (RpcError, _InjectedFault) as e:
            self.die(e)
            raise ReplicaDeadError(
                f"replica {self.name!r} unreachable: {e}") from e

    def die(self, error):
        """Mark dead (idempotent) — lease expiry and channel failure both
        land here."""
        if self.alive:
            self.alive = False
            self.error = error
        self.client.close()

    def close(self):
        self.client.close()

    # ---- EngineReplica facade ------------------------------------------------
    def submit(self, prompt_ids, **kw):
        return self._call("submit", prompt_ids=list(prompt_ids), **kw)

    def poll(self, rid, timeout=None):
        grace = None if timeout is None else float(timeout) + 30.0
        return self._call("poll", deadline=grace, rid=rid, timeout=timeout)

    def cancel(self, rid):
        return self._call("cancel", rid=rid)

    def status(self, rid):
        return self._call("status", rid=rid)

    def result(self, rid):
        return self._call("result", rid=rid)

    def request_error(self, rid):
        return self._call("request_error", rid=rid)

    def ttft(self, rid):
        try:
            return self._call("ttft", rid=rid)
        except ReplicaDeadError:
            return None

    def tpot(self, rid):
        try:
            return self._call("tpot", rid=rid)
        except ReplicaDeadError:
            return None

    def load(self):
        return self._call("load")

    def prefix_keys(self):
        return self._call("prefix_keys")

    def export_pages(self, keys):
        return self._call("pull_pages", keys=list(keys))

    def import_pages(self, payload):
        return self._call("push_pages", payload=payload)

    def health(self):
        try:
            return self._call("health")
        except ReplicaDeadError:
            return {"replica": self.name, "alive": False,
                    "error": repr(self.error)}

    def metrics(self):
        try:
            return self._call("metrics")
        except ReplicaDeadError:
            return {}

    def metrics_snapshot(self, deadline=None):
        """The worker PROCESS's full registry snapshot (federation pull)."""
        return self._call("metrics_snapshot", deadline=deadline)

    def trace_events(self, trace_id=None, deadline=None):
        """The worker's flight-recorder events for ``trace_id`` (all, when
        None) — the pull half of fleet-wide request tracing."""
        return self._call("trace_events", deadline=deadline,
                          trace_id=trace_id)

    def __repr__(self):
        return (f"RemoteReplica({self.name!r}, epoch={self.epoch}, "
                f"alive={self.alive})")


class FleetReplicaSet(ReplicaSet):
    """ReplicaSet whose members are remote workers joined via membership."""

    def __init__(self, store, group="fleet", ttl=2.0, clock=time.monotonic,
                 router=None, admission=None, requeue=True, page_size=16,
                 connect_timeout=5.0, retry_policy=None, peer_pull=False,
                 peer_pull_min_pages=1):
        # deliberately NOT calling super().__init__: the fleet starts empty
        # and fills from membership events, while the base requires engines
        self.membership = MembershipService(store, group=group, ttl=ttl,
                                            clock=clock,
                                            retry_policy=retry_policy)
        self.watcher = self.membership.watch()
        self.router = (router if router is not None
                       else PrefixAffinityRouter(page_size=page_size))
        self.admission = admission if admission is not None else AlwaysAdmit()
        self.requeue = bool(requeue)
        # peer KV tier over the worker RPC plane (pull_pages/push_pages);
        # off by default — see ReplicaSet.__init__
        self._peer_pull = bool(peer_pull)
        self._peer_pull_min = int(peer_pull_min_pages)
        self._pull_retry = RetryPolicy(max_attempts=3, base_delay=0.01,
                                       max_delay=0.25)
        self.replicas = []
        self._by_name = {}
        # members advertising role == "prefill" are disaggregation prefill
        # tiers, not serving replicas: they never enter routing; a
        # DisaggEngine lists them via remote_prefill=[...]
        self.prefill_tiers: dict = {}
        self._connect_timeout = float(connect_timeout)
        self._sync_thread = None
        self._sync_stop = threading.Event()

    # ---- membership fold -----------------------------------------------------
    def sync(self):
        """One membership tick folded into the routing table; returns the
        events it applied (deterministic — tests call this directly)."""
        events = self.watcher.poll()
        for ev in events:
            if ev.kind == JOIN:
                self._on_join(ev.member)
            else:  # LEAVE / EXPIRE
                self._on_gone(ev.member, expired=(ev.kind == EXPIRE))
        return events

    def _on_join(self, member):
        meta0 = member.meta or {}
        if meta0.get("role") == "prefill":
            old = self.prefill_tiers.pop(member.name, None)
            if old is not None:
                old.close()
            self.prefill_tiers[member.name] = RemotePrefillTier(
                meta0.get("host", "127.0.0.1"), meta0["port"],
                name=member.name, connect_timeout=self._connect_timeout)
            return
        old = self._by_name.get(member.name)
        if old is not None:
            if getattr(old, "epoch", None) == member.epoch:
                return  # already routing this incarnation
            old.die(ReplicaDeadError(
                f"superseded by epoch {member.epoch}"))
        meta = member.meta or {}
        rep = RemoteReplica(member.name, meta.get("host", "127.0.0.1"),
                            meta["port"], epoch=member.epoch,
                            connect_timeout=self._connect_timeout)
        self.add_replica(rep)
        try:
            # prefix_keys covers every tier the worker can serve without
            # recompute — resident HBM pages AND host-RAM spilled chains —
            # so a respawned worker rejoins as warm as its caches really are
            for key in rep.prefix_keys():
                self.router.note_event(rep.name, "register", key)
        except ReplicaDeadError:
            pass  # died between join and warm-up; expiry will reap it

    def _on_gone(self, member, expired):
        tier = self.prefill_tiers.pop(member.name, None)
        if tier is not None:
            tier.close()
            return
        rep = self._by_name.get(member.name)
        if rep is None:
            return
        if expired:
            # stopped heartbeating: inflight polls must fail over, not hang
            rep.die(ReplicaDeadError(
                f"replica {member.name!r} lease expired "
                f"(epoch {member.epoch})"))
        self.remove_replica(member.name)

    # ---- fleet observability -------------------------------------------------
    def _federation_members(self, attr):
        """Extend the base scrape set with the disaggregation prefill
        tiers: they are leased members with registries of their own, just
        not serving replicas, so routing skips them but federation must
        not.  They ride the base class's concurrent scrape and share its
        failure semantics."""
        members = super()._federation_members(attr)
        for name, tier in list(self.prefill_tiers.items()):
            fn = getattr(tier, attr, None)
            if fn is not None:
                members.append((name, fn))
        return members

    # ---- lifecycle -----------------------------------------------------------
    def start_sync(self, interval=0.2):
        """Apply :meth:`sync` every ``interval`` seconds from a daemon
        thread (joined by :meth:`close`)."""
        if self._sync_thread is None:
            self._sync_stop.clear()
            self._sync_thread = threading.Thread(
                target=self._sync_loop, args=(float(interval),),
                name=f"fleet-sync-{self.membership.group}", daemon=True)
            self._sync_thread.start()
        return self

    def _sync_loop(self, interval):
        while not self._sync_stop.wait(interval):
            try:
                self.sync()
            except (OSError, ConnectionError, TimeoutError):
                continue  # store hiccup: next tick retries

    def start(self):
        return self.start_sync()

    def close(self):
        self._sync_stop.set()
        if self._sync_thread is not None:
            self._sync_thread.join(timeout=10.0)
            self._sync_thread = None
        for r in self.replicas:
            r.close()
        for t in self.prefill_tiers.values():
            t.close()
        self.prefill_tiers.clear()
