"""Cross-host prefill tier: the worker side and pool side of a KV handoff
that crosses a process boundary.

A *prefill worker* is an ordinary :class:`~.worker.WorkerServer` started
with ``role="prefill"``: its engine gets a
:class:`PrefillHandoffBuffer` installed as ``prefill_sink``, so a prompt
that finishes prefilling is detached from its slot, its KV pages gathered
to host RAM (the ``pages_to_host`` spill idiom — owned numpy arrays), the
device pages released back to the worker's pool, and the serialized block
parked until the decode-side pool pulls it.  The worker advertises
``role`` in its membership lease meta, and its RPC surface grows four ops
(``handoff_ready`` / ``handoff_pull`` / ``handoff_cancel`` /
``handoff_audit``) that ride the same protocol-5 out-of-band framing as
``pull_pages``/``push_pages`` — the page block crosses the wire without an
in-band pickle copy.

:class:`RemotePrefillTier` is the pool-side handle a
:class:`~..engine.disagg.DisaggEngine` lists in ``remote_prefill=[...]``:
``submit`` routes a prompt to the worker, ``poll_ready``/``pull`` drain
finished prefills back as ``{"req", "block", "n_tokens"}`` payloads that
land through the pool's ordinary queue → stage → scatter pipeline, and
``audit`` folds the worker's page accounting into the pool's combined
refcount audit.  :class:`~.fleet.FleetReplicaSet` builds these
automatically for members that advertise ``role == "prefill"``.
"""
from __future__ import annotations

import copy
import threading

from ...observability import flight as _flight
from ..serving import RequestStatus
from .rpc import RpcClient

__all__ = ["PrefillHandoffBuffer", "RemotePrefillTier"]


class PrefillHandoffBuffer:
    """Worker-side half of a cross-host handoff: a ``prefill_sink`` that
    serializes each finished prefill to host RAM and parks it for pull.

    The sink runs on the replica's step thread with the engine condition
    held, so engine state needs no extra locking; the parked map has its
    own lock because ``ready``/``pull``/``drop`` arrive on RPC threads.
    Parked entries hold NO device pages — the block is host memory and the
    worker's pool refs are released in the sink — so a pulled-then-lost
    payload can never leak device pages, and the worker's refcount audit
    stays clean whatever the pool does."""

    def __init__(self, engine):
        self.engine = engine
        self._lock = threading.Lock()
        self._parked: dict = {}       # worker rid -> payload dict
        self.parked_total = 0         # lifetime sink count (stats)
        engine.prefill_sink = self._sink

    def _sink(self, slot, token):
        eng = self.engine
        r = eng.sched.slots[slot]
        eng.sched.emit(slot, token)
        if eng.sched.slots[slot] is not r:
            # the first token already finished it (max_new==1 / instant
            # eos): emit() finalized and released the slot — park the
            # completed request itself, nothing to transfer
            payload = {"req": copy.copy(r), "block": None, "n_tokens": 0}
        else:
            req, pages, n_tokens = eng.sched.detach(slot)
            block = eng.runner.pages_to_host(pages)
            for p in pages:          # block owns the data: device refs go,
                eng.pool.unref_page(p)   # prompt pages park in the LRU
            # copy BEFORE finalize so the payload request stays RUNNING
            # with pos == len(prompt) — exactly what admit_prefilled wants
            payload = {"req": copy.copy(req), "block": block,
                       "n_tokens": int(n_tokens)}
            eng.sched.finalize(req, RequestStatus.FINISHED)
        payload["req"].slot = None
        payload["req"].stream_pos = 0
        if r.trace_id is not None:
            _flight.record("handoff_parked", rid=r.rid, trace_id=r.trace_id,
                           n_tokens=payload["n_tokens"])
        with self._lock:
            self._parked[r.rid] = payload
            self.parked_total += 1

    def ready(self):
        """Worker rids with a parked block awaiting pull."""
        with self._lock:
            return list(self._parked)

    def pull(self, rid):
        """Hand the parked payload over (removing it).  KeyError for an
        unknown rid — the pool quarantines that request."""
        with self._lock:
            return self._parked.pop(rid)

    def drop(self, rid):
        """Discard a parked payload (pool-side cancel/poison).  True when
        something was dropped."""
        with self._lock:
            return self._parked.pop(rid, None) is not None


class RemotePrefillTier:
    """Pool-side handle to a prefill-role worker, duck-typed for
    ``DisaggEngine(remote_prefill=[...])``: submit / poll_ready / pull /
    cancel / fail / load / audit / close.  ``load()`` is the locally
    tracked inflight count (submitted minus pulled/failed) so the pool's
    least-loaded routing never pays an RPC per placement decision."""

    def __init__(self, host, port, name=None, connect_timeout=5.0,
                 call_timeout=60.0):
        self.name = str(name) if name is not None else f"{host}:{port}"
        self.client = RpcClient(host, port, connect_timeout=connect_timeout,
                                call_timeout=call_timeout)
        self._inflight = 0

    def submit(self, prompt_ids, **kw):
        rid = self.client.call("submit", ctx=_flight.wire_context(),
                               prompt_ids=list(prompt_ids), **kw)
        self._inflight += 1
        return rid

    def poll_ready(self):
        return self.client.call("handoff_ready", ctx=_flight.wire_context())

    def pull(self, rid):
        payload = self.client.call("handoff_pull",
                                   ctx=_flight.wire_context(), rid=rid)
        self._inflight = max(0, self._inflight - 1)
        return payload

    def cancel(self, rid):
        try:
            return self.client.call("handoff_cancel",
                                    ctx=_flight.wire_context(), rid=rid)
        finally:
            self._inflight = max(0, self._inflight - 1)

    # poison quarantine drops the worker-side payload the same way a
    # cancel does; the pool records the FAILED terminal on its own side
    fail = cancel

    def load(self):
        return self._inflight

    def audit(self):
        return self.client.call("handoff_audit", ctx=_flight.wire_context())

    def metrics_snapshot(self, deadline=None):
        """The prefill worker's full registry snapshot (federation pull)."""
        return self.client.call("metrics_snapshot", deadline=deadline,
                                ctx=None)

    def trace_events(self, trace_id=None, deadline=None):
        """The prefill worker's flight-recorder events for ``trace_id``."""
        return self.client.call("trace_events", deadline=deadline, ctx=None,
                                trace_id=trace_id)

    def close(self):
        self.client.close()

    def __repr__(self):
        return f"RemotePrefillTier({self.name!r}, inflight={self._inflight})"
