"""Streaming HTTP/SSE gateway over a :class:`~.replica.ReplicaSet`.

Pure stdlib (same ``ThreadingHTTPServer`` discipline as
``observability/exporter.py`` — daemon threads, handle object with
``url``/``close()``): each request runs on its own handler thread and blocks
on the replica's condition variable, so N concurrent clients cost N parked
threads, not N polling loops.

Endpoints::

    POST /v1/completions   JSON body {"prompt": [token ids],
                           "max_tokens": n, "stream": bool, ...sampling}
    GET  /healthz          per-replica health snapshots (JSON) + a
                           ``fleet`` rollup (alive/draining counts, epochs,
                           pooled page/host-tier totals)
    GET  /metrics          Prometheus text exposition — the gateway's own
                           registry FEDERATED with every live remote
                           member's snapshot, remote series labeled
                           ``replica=``; a dead member is skipped within a
                           bounded scrape deadline and counted in
                           ``frontend_federation_errors_total``
    GET  /v1/requests/{rid}/trace
                           merged chrome-trace JSON for one request:
                           span events pulled from every fleet process plus
                           the gateway's own flight recorder, causally
                           ordered by Lamport stamps — load it straight
                           into chrome://tracing / Perfetto

Every ``POST /v1/completions`` is assigned a request id — taken from the
client's ``X-Request-ID`` header when present, minted otherwise — which is
ALSO the flight-recorder trace id.  It is echoed in the ``X-Request-ID``
response header and the JSON body (``request_id``), and is what
``/v1/requests/{rid}/trace`` looks up.

Terminal-status → HTTP mapping:

    SHED      429 Too Many Requests + Retry-After (admission or engine shed;
              decided before any tokens move, stream and non-stream alike)
    TIMEOUT   408 Request Timeout + Retry-After on the non-stream path; a
              stream that times out mid-flight has already sent 200 +
              tokens, so the deadline surfaces in the final SSE event's
              ``status``
    FAILED    500 on non-stream (error string in the body) / final-event
              status on streams
    CANCELLED client disconnect mid-stream — the handler detects the broken
              pipe on write and calls ``cancel(rid)`` so the engine frees
              the request's pages instead of decoding for nobody

Stream framing is SSE: one ``data: {"token": t, "index": i}`` event per
token, then ``data: {"status": ..., "usage": ...}``, then ``data: [DONE]``.

Passing ``journal_dir`` to :func:`start_gateway` turns on the **durable
request plane** (:mod:`.journal`):

- every accepted request is journaled (fsynced) before the response
  starts, keyed by the client's ``Idempotency-Key`` header (one is
  generated when absent and echoed back) — re-POSTing a known key replays
  the journaled stream/result without re-running anything on the fleet;
- durable SSE events carry ``id: <seq>``; a reconnecting client sends
  ``Last-Event-ID: <seq>`` and the gateway replays the journaled tokens
  after it, then splices onto the live stream;
- a mid-stream disconnect *detaches* (grace TTL) instead of cancelling,
  so the client can come back;
- a restarted gateway pointed at the same ``journal_dir`` replays the
  journal and re-drives unfinished requests via the engines'
  ``resume_tokens`` machinery; while that replay runs, ``/healthz``
  reports ``recovering: true`` and new submits shed 503 + Retry-After.
"""
from __future__ import annotations

import json
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ... import observability as _obs
from ...observability import flight as _flight
from ..serving import RequestStatus
from .admission import ShedError
from .journal import DurableRequestPlane
from .replica import ReplicaDeadError

__all__ = ["Gateway", "start_gateway"]

_SAMPLING_KEYS = ("eos_token_id", "do_sample", "temperature", "top_p",
                  "top_k", "seed", "deadline")


class Gateway:
    """Handle on a running gateway: ``addr``/``port``/``url`` + ``close()``.
    Owns the HTTP server only — the ReplicaSet's lifecycle stays with its
    creator (``close()`` does not stop the replicas)."""

    def __init__(self, httpd, thread, replica_set, plane=None):
        self._httpd = httpd
        self._thread = thread
        self.replica_set = replica_set
        self.plane = plane          # DurableRequestPlane in durable mode
        self.addr, self.port = httpd.server_address[:2]
        self.url = f"http://{self.addr}:{self.port}"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10.0)
        if self.plane is not None:
            # pumps stop, journal closes; inflight requests keep their
            # unjournaled-terminal state so a restart recovers them
            self.plane.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    replica_set = None       # bound per-server by start_gateway
    plane = None             # DurableRequestPlane, durable mode only
    ping_interval = 5.0      # idle seconds between SSE keep-alive comments
    request_id = None        # per-POST trace id (X-Request-ID)

    # ---- GET -----------------------------------------------------------------
    def do_GET(self):  # noqa: N802 (stdlib handler API)
        # instance state persists across requests on a keep-alive socket:
        # clear the id so a GET never echoes the previous POST's header
        self.request_id = None
        path = self.path.split("?")[0]
        if path == "/healthz":
            health = dict(self.replica_set.health())
            if self.plane is not None:
                # "journal" is a reserved key in durable mode (don't name a
                # replica that): journal depth + recovery state ride along
                health["journal"] = self.plane.health()
            # "fleet" is reserved too: the rollup external monitors page on
            # without walking every per-replica snapshot
            health["fleet"] = self._fleet_rollup(health)
            self._send_json(200, health)
        elif path == "/metrics":
            # federated exposition when the replica set can scrape its
            # members; a bare duck-typed set falls back to local-only
            fed = getattr(self.replica_set, "metrics_exposition", None)
            text = fed() if fed is not None else _obs.render_prometheus()
            body = text.encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path.startswith("/v1/requests/") and path.endswith("/trace"):
            rid = path[len("/v1/requests/"):-len("/trace")]
            if not rid or "/" in rid:
                self._send_json(404, {"error": f"no route for {path}"})
                return
            fn = getattr(self.replica_set, "trace_events_fleet", None)
            events = (fn(rid) if fn is not None
                      else _flight.snapshot_events(rid))
            if not events:
                self._send_json(404,
                                {"error": f"no trace for request {rid!r}"})
                return
            self._send_json(200, _flight.chrome_trace(events))
        else:
            self._send_json(404, {"error": f"no route for {path}"})

    @staticmethod
    def _fleet_rollup(health):
        """Aggregate the per-replica snapshots into one fleet summary:
        liveness/draining counts, per-replica epochs, and pooled page
        totals (device free/reclaimable + host tier)."""
        rollup = {"replicas": 0, "alive": 0, "draining": 0, "epochs": {},
                  "active_slots": 0, "waiting": 0, "free_pages": 0,
                  "reclaimable_pages": 0, "host_cached_pages": 0,
                  "host_bytes": 0}
        for name, snap in health.items():
            if name in ("journal", "fleet") or not isinstance(snap, dict):
                continue
            rollup["replicas"] += 1
            if snap.get("alive"):
                rollup["alive"] += 1
            if snap.get("draining"):
                rollup["draining"] += 1
            if snap.get("epoch") is not None:
                rollup["epochs"][name] = snap["epoch"]
            for k in ("active_slots", "waiting", "free_pages",
                      "reclaimable_pages", "host_cached_pages",
                      "host_bytes"):
                v = snap.get(k)
                if isinstance(v, (int, float)):
                    rollup[k] += v
        return rollup

    # ---- POST /v1/completions ------------------------------------------------
    def do_POST(self):  # noqa: N802 (stdlib handler API)
        # cleared before parsing: a 400/404 on this request must not carry
        # the prior keep-alive request's X-Request-ID
        self.request_id = None
        if self.path.split("?")[0] != "/v1/completions":
            self._send_json(404, {"error": f"no route for {self.path}"})
            return
        try:
            req = self._read_body()
            prompt = req["prompt"]
            if not isinstance(prompt, list) or not all(
                    isinstance(t, int) for t in prompt):
                raise ValueError("'prompt' must be a list of token ids")
            kw = {k: req[k] for k in _SAMPLING_KEYS if k in req}
            kw["max_new_tokens"] = int(req.get("max_tokens", 16))
            stream = bool(req.get("stream", False))
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        # one request id per accepted POST — the client's X-Request-ID when
        # present, minted otherwise — doubling as the flight-recorder trace
        # id; the ambient context threads it through routing, the durable
        # plane, RPC frames, and the engines without touching signatures
        _flight.set_proc_label("gateway")
        ctx = _flight.mint(self.headers.get("X-Request-ID") or None)
        self.request_id = ctx.trace_id
        with _flight.use_context(ctx):
            _flight.record("gateway_accept", trace_id=ctx.trace_id,
                           prompt_tokens=len(prompt), stream=stream)
            if self.plane is not None:
                self._durable_completion(prompt, kw, stream)
                return
            try:
                handle = self.replica_set.submit(prompt, **kw)
            except ShedError as e:
                self._send_json(429, {"error": str(e), "reason": e.reason},
                                headers={"Retry-After":
                                         str(max(1, int(e.retry_after)))})
                return
            except ReplicaDeadError as e:
                # dead fleet: carry Retry-After like the SHED 429 does, so
                # clients back off instead of hot-looping on 503s
                self._send_json(503, {"error": str(e)},
                                headers={"Retry-After": "1"})
                return
            except ValueError as e:
                self._send_json(400, {"error": str(e)})
                return
            if stream:
                self._stream_response(handle)
            else:
                self._blocking_response(handle)

    def _blocking_response(self, handle):
        rs = self.replica_set
        tokens, status = rs.result(handle)
        if status is RequestStatus.TIMEOUT and not tokens:
            # Retry-After parity with 429/503: an unserved deadline is a
            # load symptom, the client should back off before re-asking
            self._send_json(408, {"error": "deadline expired unserved",
                                  "status": status.value},
                            headers={"Retry-After": "1"})
            return
        if status is RequestStatus.FAILED:
            self._send_json(500, {"error": rs.request_error(handle),
                                  "status": status.value})
            return
        _flight.record("gateway_done", trace_id=self.request_id,
                       status=status.value, tokens=len(tokens))
        self._send_json(200, {
            "replica": handle.replica.name,
            "request_id": self.request_id,
            "status": status.value,
            "tokens": tokens,
            "usage": {"completion_tokens": len(tokens)},
        })

    def _stream_response(self, handle):
        rs = self.replica_set
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-store")
        self.send_header("Connection", "close")
        if self.request_id is not None:
            self.send_header("X-Request-ID", self.request_id)
        # SSE has no predeclared length; closing the socket ends the stream
        self.close_connection = True
        self.end_headers()
        try:
            i = 0
            for tok in rs.stream(handle, heartbeat=self.ping_interval):
                if tok is None:
                    # idle keep-alive: proxies don't sever a silent stream
                    # during a long prefill/queue wait, and a client that
                    # dropped before the first token fails THIS write — the
                    # except below then cancels on the replica instead of
                    # decoding for nobody
                    self.wfile.write(b": ping\n\n")
                    self.wfile.flush()
                    continue
                self._sse({"token": int(tok), "index": i})
                i += 1
            status = rs.status(handle)
            _flight.record("gateway_done", trace_id=self.request_id,
                           status=status.value, tokens=i)
            final = {"status": status.value,
                     "replica": handle.replica.name,
                     "request_id": self.request_id,
                     "usage": {"completion_tokens": i}}
            if status is RequestStatus.FAILED:
                final["error"] = rs.request_error(handle)
            self._sse(final)
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionError, OSError):
            # client went away mid-stream: stop decoding for nobody
            rs.cancel(handle)

    # ---- durable mode (journal-backed) ---------------------------------------
    def _durable_completion(self, prompt, kw, stream):
        plane = self.plane
        if plane.recovering:
            # journal replay owns the fleet right now; shed instead of
            # interleaving fresh admissions with re-driven requests
            self._send_json(503, {"error": "gateway recovering",
                                  "recovering": True},
                            headers={"Retry-After": "1"})
            return
        key = self.headers.get("Idempotency-Key") or uuid.uuid4().hex
        last_id = self.headers.get("Last-Event-ID")
        try:
            after = 0 if last_id is None else int(last_id) + 1
        except ValueError:
            self._send_json(400, {"error":
                                  f"bad Last-Event-ID {last_id!r}"})
            return
        req = plane.get(key)
        if req is not None:
            # replayed key: serve from the journaled request, never re-run
            if last_id is not None:
                _obs.STREAM_REATTACH.inc()
        else:
            try:
                req, _created = plane.submit(key, prompt, kw)
            except ShedError as e:
                self._send_json(429, {"error": str(e), "reason": e.reason},
                                headers={"Retry-After":
                                         str(max(1, int(e.retry_after)))})
                return
            except ReplicaDeadError as e:
                self._send_json(503, {"error": str(e)},
                                headers={"Retry-After": "1"})
                return
            except ValueError as e:
                self._send_json(400, {"error": str(e)})
                return
            except Exception as e:  # noqa: BLE001 — journal append failed
                # acceptance could not be made durable, so it did not happen
                self._send_json(500, {"error": f"journal append failed: "
                                               f"{e}"})
                return
        if stream:
            self._durable_stream(req, after)
        else:
            self._durable_blocking(req, key)

    def _durable_blocking(self, req, key):
        tokens, status = req.wait_terminal()
        if status is RequestStatus.TIMEOUT and not tokens:
            self._send_json(408, {"error": "deadline expired unserved",
                                  "status": status.value,
                                  "idempotency_key": key},
                            headers={"Retry-After": "1"})
            return
        if status is RequestStatus.FAILED:
            self._send_json(500, {"error": req.error,
                                  "status": status.value,
                                  "idempotency_key": key})
            return
        self._send_json(200, {
            "status": status.value,
            "tokens": tokens,
            "idempotency_key": key,
            "usage": {"completion_tokens": len(tokens)},
        })

    def _durable_stream(self, req, after):
        plane = self.plane
        plane.attach(req)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-store")
            self.send_header("Connection", "close")
            if self.request_id is not None:
                self.send_header("X-Request-ID", self.request_id)
            self.send_header("Idempotency-Key", req.key)
            self.close_connection = True
            self.end_headers()
            for ev in req.events(after=after,
                                 heartbeat=self.ping_interval):
                if ev is None:
                    self.wfile.write(b": ping\n\n")
                    self.wfile.flush()
                    continue
                seq, tok = ev
                # id: <seq> is what a reconnecting client echoes back as
                # Last-Event-ID — replay resumes AFTER this event
                self.wfile.write(b"id: %d\n" % seq)
                self._sse({"token": tok, "index": seq})
            final = {"status": req.status.value,
                     "usage": {"completion_tokens": len(req.tokens)}}
            if req.status is RequestStatus.FAILED:
                final["error"] = req.error
            self._sse(final)
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionError, OSError):
            # client went away pre-terminal: DETACH, don't cancel — the
            # grace TTL gives it a reconnect window (plane pump cancels
            # only once the window lapses with nobody attached)
            pass
        finally:
            plane.detach(req)

    # ---- plumbing ------------------------------------------------------------
    def _read_body(self):
        n = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(n) if n else b"{}"
        return json.loads(raw.decode("utf-8"))

    def _sse(self, obj):
        self.wfile.write(b"data: " + json.dumps(obj).encode("utf-8")
                         + b"\n\n")
        self.wfile.flush()

    def _send_json(self, code, obj, headers=None):
        body = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self.request_id is not None:
            self.send_header("X-Request-ID", self.request_id)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):    # requests are metered, not log events
        pass


def start_gateway(replica_set, port=0, addr="127.0.0.1", ping_interval=5.0,
                  journal_dir=None, detach_ttl=30.0,
                  journal_fsync="critical", recover=True):
    """Serve ``replica_set`` at ``http://addr:port`` from a daemon thread;
    ``port=0`` lets the OS pick (read it back from the returned handle).
    The caller owns the handle: ``close()`` stops the HTTP server (the
    replicas keep running until their owner closes them).  ``ping_interval``
    is the idle-stream keep-alive cadence (seconds between ``: ping`` SSE
    comments while no token is ready).

    ``journal_dir`` turns on the durable request plane (see module
    docstring): requests journal to that directory, submits become
    idempotent, streams resumable, and — with ``recover=True`` — any
    journal left by a previous gateway replays in a background thread
    (``/healthz`` shows ``recovering`` until it lands; submits shed 503
    meanwhile).  ``detach_ttl`` is the seconds a fully-disconnected
    pre-terminal stream survives before cancellation; ``journal_fsync``
    is the :class:`~.journal.RequestJournal` fsync policy."""
    plane = None
    if journal_dir is not None:
        plane = DurableRequestPlane(replica_set, journal_dir,
                                    fsync=journal_fsync,
                                    detach_ttl=detach_ttl)
        if recover:
            # flagged before the serving thread exists so no request can
            # slip in ahead of the replay
            plane.recovering = True
            threading.Thread(target=plane.recover,
                             name="paddle-tpu-gateway-recover",
                             daemon=True).start()
    handler = type("_BoundHandler", (_Handler,),
                   {"replica_set": replica_set,
                    "plane": plane,
                    "ping_interval": float(ping_interval)})
    httpd = ThreadingHTTPServer((addr, port), handler)
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever,
                              name="paddle-tpu-gateway", daemon=True)
    thread.start()
    return Gateway(httpd, thread, replica_set, plane=plane)
