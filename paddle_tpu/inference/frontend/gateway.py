"""Streaming HTTP/SSE gateway over a :class:`~.replica.ReplicaSet`.

Pure stdlib (same ``ThreadingHTTPServer`` discipline as
``observability/exporter.py`` — daemon threads, handle object with
``url``/``close()``): each request runs on its own handler thread and blocks
on the replica's condition variable, so N concurrent clients cost N parked
threads, not N polling loops.

Endpoints::

    POST /v1/completions   JSON body {"prompt": [token ids],
                           "max_tokens": n, "stream": bool, ...sampling}
    GET  /healthz          per-replica health snapshots (JSON)
    GET  /metrics          Prometheus text exposition of the registry

Terminal-status → HTTP mapping:

    SHED      429 Too Many Requests + Retry-After (admission or engine shed;
              decided before any tokens move, stream and non-stream alike)
    TIMEOUT   408 Request Timeout on the non-stream path; a stream that
              times out mid-flight has already sent 200 + tokens, so the
              deadline surfaces in the final SSE event's ``status``
    FAILED    500 on non-stream (error string in the body) / final-event
              status on streams
    CANCELLED client disconnect mid-stream — the handler detects the broken
              pipe on write and calls ``cancel(rid)`` so the engine frees
              the request's pages instead of decoding for nobody

Stream framing is SSE: one ``data: {"token": t, "index": i}`` event per
token, then ``data: {"status": ..., "usage": ...}``, then ``data: [DONE]``.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ... import observability as _obs
from ..serving import RequestStatus
from .admission import ShedError
from .replica import ReplicaDeadError

__all__ = ["Gateway", "start_gateway"]

_SAMPLING_KEYS = ("eos_token_id", "do_sample", "temperature", "top_p",
                  "top_k", "seed", "deadline")


class Gateway:
    """Handle on a running gateway: ``addr``/``port``/``url`` + ``close()``.
    Owns the HTTP server only — the ReplicaSet's lifecycle stays with its
    creator (``close()`` does not stop the replicas)."""

    def __init__(self, httpd, thread, replica_set):
        self._httpd = httpd
        self._thread = thread
        self.replica_set = replica_set
        self.addr, self.port = httpd.server_address[:2]
        self.url = f"http://{self.addr}:{self.port}"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    replica_set = None       # bound per-server by start_gateway
    ping_interval = 5.0      # idle seconds between SSE keep-alive comments

    # ---- GET -----------------------------------------------------------------
    def do_GET(self):  # noqa: N802 (stdlib handler API)
        path = self.path.split("?")[0]
        if path == "/healthz":
            self._send_json(200, self.replica_set.health())
        elif path == "/metrics":
            body = _obs.render_prometheus().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._send_json(404, {"error": f"no route for {path}"})

    # ---- POST /v1/completions ------------------------------------------------
    def do_POST(self):  # noqa: N802 (stdlib handler API)
        if self.path.split("?")[0] != "/v1/completions":
            self._send_json(404, {"error": f"no route for {self.path}"})
            return
        try:
            req = self._read_body()
            prompt = req["prompt"]
            if not isinstance(prompt, list) or not all(
                    isinstance(t, int) for t in prompt):
                raise ValueError("'prompt' must be a list of token ids")
            kw = {k: req[k] for k in _SAMPLING_KEYS if k in req}
            kw["max_new_tokens"] = int(req.get("max_tokens", 16))
            stream = bool(req.get("stream", False))
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        try:
            handle = self.replica_set.submit(prompt, **kw)
        except ShedError as e:
            self.send_response(429)
            body = json.dumps({"error": str(e),
                               "reason": e.reason}).encode("utf-8")
            self.send_header("Retry-After", str(max(1, int(e.retry_after))))
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        except ReplicaDeadError as e:
            # dead fleet: carry Retry-After like the SHED 429 does, so
            # clients back off instead of hot-looping on 503s
            self._send_json(503, {"error": str(e)},
                            headers={"Retry-After": "1"})
            return
        except ValueError as e:
            self._send_json(400, {"error": str(e)})
            return
        if stream:
            self._stream_response(handle)
        else:
            self._blocking_response(handle)

    def _blocking_response(self, handle):
        rs = self.replica_set
        tokens, status = rs.result(handle)
        if status is RequestStatus.TIMEOUT and not tokens:
            self._send_json(408, {"error": "deadline expired unserved",
                                  "status": status.value})
            return
        if status is RequestStatus.FAILED:
            self._send_json(500, {"error": rs.request_error(handle),
                                  "status": status.value})
            return
        self._send_json(200, {
            "replica": handle.replica.name,
            "status": status.value,
            "tokens": tokens,
            "usage": {"completion_tokens": len(tokens)},
        })

    def _stream_response(self, handle):
        rs = self.replica_set
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-store")
        self.send_header("Connection", "close")
        # SSE has no predeclared length; closing the socket ends the stream
        self.close_connection = True
        self.end_headers()
        try:
            i = 0
            for tok in rs.stream(handle, heartbeat=self.ping_interval):
                if tok is None:
                    # idle keep-alive: proxies don't sever a silent stream
                    # during a long prefill/queue wait, and a client that
                    # dropped before the first token fails THIS write — the
                    # except below then cancels on the replica instead of
                    # decoding for nobody
                    self.wfile.write(b": ping\n\n")
                    self.wfile.flush()
                    continue
                self._sse({"token": int(tok), "index": i})
                i += 1
            status = rs.status(handle)
            final = {"status": status.value,
                     "replica": handle.replica.name,
                     "usage": {"completion_tokens": i}}
            if status is RequestStatus.FAILED:
                final["error"] = rs.request_error(handle)
            self._sse(final)
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionError, OSError):
            # client went away mid-stream: stop decoding for nobody
            rs.cancel(handle)

    # ---- plumbing ------------------------------------------------------------
    def _read_body(self):
        n = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(n) if n else b"{}"
        return json.loads(raw.decode("utf-8"))

    def _sse(self, obj):
        self.wfile.write(b"data: " + json.dumps(obj).encode("utf-8")
                         + b"\n\n")
        self.wfile.flush()

    def _send_json(self, code, obj, headers=None):
        body = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):    # requests are metered, not log events
        pass


def start_gateway(replica_set, port=0, addr="127.0.0.1", ping_interval=5.0):
    """Serve ``replica_set`` at ``http://addr:port`` from a daemon thread;
    ``port=0`` lets the OS pick (read it back from the returned handle).
    The caller owns the handle: ``close()`` stops the HTTP server (the
    replicas keep running until their owner closes them).  ``ping_interval``
    is the idle-stream keep-alive cadence (seconds between ``: ping`` SSE
    comments while no token is ready)."""
    handler = type("_BoundHandler", (_Handler,),
                   {"replica_set": replica_set,
                    "ping_interval": float(ping_interval)})
    httpd = ThreadingHTTPServer((addr, port), handler)
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever,
                              name="paddle-tpu-gateway", daemon=True)
    thread.start()
    return Gateway(httpd, thread, replica_set)
