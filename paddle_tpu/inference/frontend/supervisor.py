"""Worker supervision: bounded-backoff respawn with a crash-loop breaker.

One :class:`WorkerSupervisor` owns one worker process.  Its ``spawn``
callable returns a process handle (anything with ``poll() -> exit code or
None``, ``terminate()``, ``kill()``, ``wait(timeout)`` — ``subprocess.Popen``
verbatim; tests inject fakes), and each supervision tick asks: still
running?  If not, the crash is recorded against a sliding window:

- fewer than ``max_crashes`` crashes inside ``crash_window`` seconds →
  sleep the bounded exponential backoff (``base_delay * multiplier**streak``
  capped at ``max_delay``) and respawn; ``frontend_replica_restarts_total``
  counts it.  The respawned worker re-registers its lease under a NEW
  epoch, so the membership plane never confuses it with its dead
  incarnation.
- ``max_crashes`` crashes in the window → the replica is **quarantined**:
  no further respawns, ``frontend_replica_quarantines_total`` fires, the
  member's lease is evicted from the membership group (when the supervisor
  holds a ``membership`` handle) so routers drop it on their next sync
  instead of waiting out the TTL, and the optional ``on_quarantine`` alert
  hook runs once.  A human (or a higher-level operator loop)
  un-quarantines by calling :meth:`reset`.

Clock and sleep are injectable, and :meth:`tick` is a plain synchronous
step — the deterministic tests drive crash schedules through fake handles
and a fake clock with zero wall time.  :meth:`start` wraps ``tick`` in a
daemon thread (joined by :meth:`stop`) for real deployments.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from ... import observability as _obs

__all__ = ["WorkerSupervisor"]

RUNNING, RESPAWNED, QUARANTINED, STOPPED = (
    "running", "respawned", "quarantined", "stopped")


class WorkerSupervisor:
    """Keep one worker process alive until it crash-loops."""

    def __init__(self, spawn, name="worker", base_delay=0.1, max_delay=5.0,
                 multiplier=2.0, crash_window=30.0, max_crashes=5,
                 clock=time.monotonic, sleep=time.sleep, on_quarantine=None,
                 membership=None):
        """``membership``: optional
        :class:`~paddle_tpu.distributed.membership.MembershipService`
        handle for the worker's group.  A quarantine then proactively
        ``evict()``s the worker's lease — the dead incarnation cannot
        release it, and without eviction the router keeps selecting the
        quarantined member until the TTL expires it."""
        if max_crashes < 1:
            raise ValueError("max_crashes must be >= 1")
        self.spawn = spawn
        self.name = str(name)
        self.membership = membership
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.crash_window = float(crash_window)
        self.max_crashes = int(max_crashes)
        self.clock = clock
        self.sleep = sleep
        self.on_quarantine = on_quarantine
        self.proc = None
        self.restarts = 0
        self.quarantined = False
        self.stopped = False
        self._crashes = deque()        # clock() stamps inside the window
        self._thread = None
        self._stop = threading.Event()
        self._lock = threading.Lock()

    # ---- supervision ---------------------------------------------------------
    def start_worker(self):
        """Spawn the initial worker (idempotent)."""
        with self._lock:
            if self.proc is None and not self.stopped:
                self.proc = self.spawn()
        return self

    def tick(self):
        """One supervision step; returns the resulting state string.

        Synchronous and injectable-clock deterministic: a crashed child is
        either respawned (after the backoff ``sleep``) or quarantined right
        here."""
        delay = None
        with self._lock:
            if self.stopped:
                return STOPPED
            if self.quarantined:
                return QUARANTINED
            if self.proc is None:
                self.proc = self.spawn()
                return RESPAWNED
            if self.proc.poll() is None:
                return RUNNING
            # child exited without us stopping it: a crash
            now = float(self.clock())
            self._crashes.append(now)
            while self._crashes and now - self._crashes[0] > self.crash_window:
                self._crashes.popleft()
            if len(self._crashes) >= self.max_crashes:
                self.quarantined = True
                self.proc = None
                _obs.FRONTEND_QUARANTINES.inc(replica=self.name)
                hook = self.on_quarantine
                # evict the dead incarnation's lease NOW (outside the lock —
                # store round-trips): watchers see `leave` on their next
                # poll instead of routing to a quarantined member for the
                # rest of the TTL
            else:
                self.proc = None
                streak = len(self._crashes) - 1
                delay = min(self.max_delay,
                            self.base_delay * self.multiplier ** streak)
        if delay is not None:
            # backoff OUTSIDE the lock: a concurrent stop()/reset() must
            # not block behind up to max_delay of sleep, and a stop that
            # lands mid-backoff wins — re-check before respawning
            self.sleep(delay)
            with self._lock:
                if self.stopped:
                    return STOPPED
                if self.quarantined:
                    return QUARANTINED
                self.proc = self.spawn()
                self.restarts += 1
            _obs.FRONTEND_RESTARTS.inc(replica=self.name)
            return RESPAWNED
        if self.membership is not None:
            try:
                self.membership.evict(self.name)
            except (OSError, ConnectionError, TimeoutError):
                pass  # store unreachable: the TTL expiry path still reaps
        if hook is not None:
            hook(self)
        return QUARANTINED

    def reset(self):
        """Clear quarantine + crash history (operator action); the next
        :meth:`tick` respawns."""
        with self._lock:
            self.quarantined = False
            self._crashes.clear()

    # ---- background loop -----------------------------------------------------
    def start(self, interval=0.2):
        """Run :meth:`tick` every ``interval`` seconds in a daemon thread
        until :meth:`stop` (which joins it)."""
        self.start_worker()
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, args=(float(interval),),
                name=f"supervisor-{self.name}", daemon=True)
            self._thread.start()
        return self

    def _loop(self, interval):
        while not self._stop.wait(interval):
            if self.tick() in (QUARANTINED, STOPPED):
                return

    def stop(self, term_timeout=10.0):
        """Stop supervising and shut the child down: SIGTERM (graceful
        drain), bounded wait, SIGKILL as the backstop."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        with self._lock:
            self.stopped = True
            proc, self.proc = self.proc, None
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=term_timeout)
            except Exception:
                proc.kill()
                proc.wait(timeout=5.0)
